"""GossipSub simulator: mesh overlay + lazy gossip, every peer at once.

The vectorized counterpart of the protocol core's GossipSubRouter
(core/gossipsub.py; reference /root/reference/gossipsub.go).  One jitted
``step`` advances one heartbeat for ALL simulated peers: mesh forwarding,
IHAVE/IWANT gossip repair, then the heartbeat maintenance pass
(graft-to-D / prune-to-D, backoff, fanout TTL — gossipsub.go:1299-1552).

TPU-first representation (see PERF_NOTES.md):

- **Topology = per-topic random circulants.**  Peer p belongs to topic
  ``p mod T``; the candidate-neighbor set of every peer is a static list of
  C ring offsets, all multiples of T and closed under negation.  Candidates
  model what discovery + peer exchange give a deployed node: the topic
  peers it *could* connect to (discovery.go:108-173, PX gossipsub.go:856).
  With ``paired_topics`` every peer additionally subscribes its pair
  topic ``p mod T + T/2`` and keeps a SECOND mesh/backoff for it
  (offsets become multiples of T/2, so each candidate shares both
  topics); per-topic score contributions sum under TopicScoreCap — see
  GossipSimConfig.paired_topics and tests/test_gossipsub_paired.py.
- **Mesh/fanout/eligibility/handshake masks are uint32 bitmasks [N]** over
  the candidate bits (C <= 32).  GRAFT/PRUNE flip bits; degree = popcount;
  all the mask logic of the heartbeat is single-word elementwise ops at
  4 bytes/peer — the same bit-packing as message possession.  Degree
  bounds (D/Dhi, gossipsub.go:33-40) keep C a small compile-time constant.
- **Peer-minor layout everywhere.**  The peer axis is the LAST axis of
  every dense array ([C, N] score counters, [W, N] possession words), so
  it sits on the TPU's 128 vector lanes: full-bandwidth elementwise ops
  and contiguous [N] rows whose 1D rolls are ~12x faster than 2D column
  rolls.
- **Edge duality is a bit permutation + roll.**  The link (p, p+o_c) seen
  from the partner is bit ``cinv[c]`` where ``o_cinv = -o_c``, so sending
  a mask to the partners is roll bit c by o_c into bit cinv[c]
  (transfer_bits) — no gathers, no stacks.
- **Selection is rank-compare, not sort.**  Top-k by random or score
  priority is an all-pairs C² comparison count (ranks_desc) — ~6x faster
  than argsort at C=16 — wrapped in expand/pack so inputs and outputs
  stay packed.
- **Messages are bit positions** in uint32 words, as in models/floodsub.py.
  The mcache (mcache.go) becomes a ROTATING ring of recently-acquired
  words: slot (t-1) mod HistoryGossip holds the newest heartbeat window,
  and each tick overwrites one slot in place (no full-ring shift); IHAVE
  advertises the OR of all HistoryGossip slots (mcache.go:82,
  GetGossipIDs — order-independent, so slot rotation is free).

Timing model: one tick = one heartbeat = one network hop.  Reachability is
measured in hops (publish-tick-relative), which is exactly the
reachability-vs-hops contract from BASELINE.md and independent of the
wall-clock heartbeat/RTT ratio.

Design bound — topic membership is k <= 2 per peer (paired mode), by
decision rather than omission:

- The reference's per-peer score is a weighted linear fold over
  per-topic terms (score.go:264-316).  Paired mode exercises every
  term class of that fold at k = 2: per-slot P1, delivery-driven
  P2/P4 summed across the pair, the cap binding on a true multi-topic
  sum, per-topic meshes/backoffs, and the cross-slot control routing
  (class(p+o) = class(p) + T/2 on odd edges).  k = 3 or 4 repeats the
  same fold and routing mechanism with more cases — no new interaction
  class appears, while mesh/backoff/P1 state, the maintenance
  selections, and the handshake transfers all multiply by k (the
  pair-packed transfer tops out at two 16-bit masks per u32 word, so
  k > 2 also forfeits the packed-handshake optimization).
- Arbitrary-k membership with the EXACT per-topic weighted sum is
  already expressible in the framework — in the protocol core
  (core/score.py mirrors score.go:256-333 with per-topic params and
  arbitrary topic sets), which is the semantics oracle the sim is
  validated against (interop/replay.py).  The sim trades arbitrary-k
  for the circulant scale design; the 100-topic flagship covers
  many-topic scale, the paired overlay covers overlap dynamics.
- Equal pair weights keep the aggregated P2 fold EXACT (P2 is linear);
  P4 aggregation is exact when one topic carries the invalid traffic
  (the adversarial configs) and conservative otherwise (the squared
  aggregate >= the per-topic sum of squares at equal weights).
  Unequal weights remain expressible in the core.

Known deviation — same-tick P2/P4 delivery credit: the reference credits
FirstMessageDeliveries to exactly one peer (score.go
markFirstMessageDelivery) and routes duplicates to mesh-delivery credit
only; this sim credits EVERY same-tick deliverer of a new message (one
tick = the near-first window, score.go:684-818).  With mesh in-degree D
this can inflate P2 by up to ~D per message relative to a serial
first-claim, but it is unbiased w.r.t. candidate-bit order and columns
stay independent (vectorizable).  The steady-state effect is a uniform
scale on P2 across honest peers (they share the same in-degree
distribution), so relative ranking — what the thresholds act on — is
preserved; test_same_tick_credit_uniform_scale quantifies it against a
serial-claim replay on a small graph.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import ClassVar

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops.graph import (
    WORD_BITS,
    bit_row,
    count_bits_per_position,
    expand_bits,
    lane_seed,
    lane_uniform,
    make_circulant_offsets,
    pack_bits,
    pack_rows,
    popcount32,
    ranks_desc,
    select_k_bits,
    select_k_by_priority_bits,
)
from ._batch import index_trees, stack_trees, tree_copy  # noqa: F401
#   (re-exported: tree_copy is the companion of the donated runners —
#    callers that reuse a state after a run pass a copy)
from ._delivery import (
    reach_counts_from_first_tick,
    first_tick_to_matrix,
    update_first_tick,
)
from . import delays as _delays
from . import faults as _faults
from . import plan as _plan
from . import invariants as _invariants
from . import knobs as _knobs
from . import telemetry as _telemetry
from .knobs import SimKnobs, KnobStaticFieldError  # noqa: F401
#   (re-exported: the sweep engine's user surface — models/knobs.py)


# --------------------------------------------------------------------------
# Static configuration (baked into the compiled step as constants)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class GossipSimConfig:
    """Static simulator config.  Protocol defaults mirror GossipSubParams
    (core/gossipsub.py:61; reference gossipsub.go:31-59)."""

    offsets: tuple[int, ...]       # C candidate ring offsets, ± paired
    n_topics: int = 1
    # PX rotation toggle (only meaningful with make_gossip_sim's
    # px_candidates): when False the active candidate set is frozen —
    # the no-peer-exchange control for recovery experiments.
    px_rotation: bool = True
    # paired-topic mode: every peer subscribes TWO topics — its residue
    # class r = p mod T and r + T/2 — and keeps a separate mesh per
    # topic slot.  Offsets are then multiples of T/2 (not T), so each
    # candidate shares BOTH topics with its partner and the per-topic
    # circulants stay closed over the union of the two classes.  With
    # equal topic weights the per-topic score sum uses the aggregate
    # delivery counters plus per-slot P1 terms (see compute_scores).
    paired_topics: bool = False
    d: int = 6                     # GossipSubD
    d_lo: int = 5                  # GossipSubDlo
    d_hi: int = 12                 # GossipSubDhi
    d_score: int = 4               # GossipSubDscore (v1.1 prune retention)
    d_out: int = 2                 # GossipSubDout (outbound quota)
    d_lazy: int = 6                # GossipSubDlazy
    gossip_factor: float = 0.25    # GossipSubGossipFactor
    history_gossip: int = 3        # GossipSubHistoryGossip (IHAVE window)
    history_length: int = 5        # GossipSubHistoryLength (mcache span)
    backoff_ticks: int = 60        # GossipSubPruneBackoff / heartbeat
    fanout_ttl_ticks: int = 60     # GossipSubFanoutTTL / heartbeat
    # gossip-repair abuse bounds (gossipsub.go:56-59, mcache.go:66-80):
    # a message is retransmitted to one peer at most gossip_retransmission
    # times before that peer's IWANTs for it are ignored (the serve
    # ledger is ALWAYS-ON when scoring is — see GossipState.
    # iwant_serves).  The IHAVE advert caps are STATICALLY enforced
    # invariants rather than run-time truncation: the sim's whole id
    # space (one bit per message) must fit a single IHAVE
    # (make_gossip_sim rejects n_msgs > max_ihave_length), and the sim
    # emits exactly ONE merged IHAVE per edge per tick, within
    # max_ihave_messages >= 1 by construction — so a config the sim
    # accepts can never exceed either reference cap.
    gossip_retransmission: int = 3   # GossipSubGossipRetransmission
    max_ihave_length: int = 5000     # GossipSubMaxIHaveLength
    max_ihave_messages: int = 10     # GossipSubMaxIHaveMessages
    # Gossip-target sampling backend.  The reference draws an exact
    # uniform k-subset of the eligible peers per heartbeat (emitGossip
    # gossipsub.go:1656-1712).  True = per-edge Bernoulli(k/|elig|):
    # identical per-edge inclusion probability, so gossip coverage and
    # every score/penalty rate driven by it match in expectation; only
    # the per-peer target-count distribution widens (binomial vs
    # degenerate, same mean — the CLT equivalence argument documented
    # for the RandomSub fanout, models/randomsub.py).  On TPU the
    # Bernoulli form is one hashed-uniform compare, while exact-k needs
    # the [C, C, N] rank-compare — ~600 us/tick of the v1.1 flagship
    # step, the single largest always-on cost after the payload rolls.
    # False restores exact-k (validation/equivalence studies).
    binomial_gossip_sampling: bool = True

    # Machine-readable thread-or-refuse contract, verified by
    # tools/graftlint/contracts.py: every field must be provably
    # "threaded" (reaches the compiled step — as a baked constant or
    # through built device arrays — on EVERY path in PATHS, proven by
    # jaxpr/build diff under a probe value) or "build-time" (host-side
    # validation only, proven by a reject probe that raises).  A new
    # config field without a contract entry (or an entry without a
    # probe) fails `python -m tools.graftlint`.
    PATHS: ClassVar[tuple[str, ...]] = ("xla", "kernel")
    # round 12: every liftable numeric field is "traced" — threaded
    # (baked) AND provably liftable to a SimKnobs operand with NO
    # retrace across knob values (models/knobs.py; the prover runs
    # both proofs).  Shape-bearing fields stay "threaded" (baked
    # only) and are rejected by the knob surface by name.  The one
    # exception: gossip_retransmission stays baked-threaded on the
    # kernel path (its serve-budget multiply runs in-kernel; the
    # kernel refuses knob points on iwant-spam configs — see
    # SimKnobs.CONTRACT for the matching refusal).
    CONTRACT: ClassVar[dict[str, object]] = {
        "offsets": "threaded",
        "n_topics": "threaded",
        "px_rotation": "threaded",
        "paired_topics": "threaded",
        "d": "traced",
        "d_lo": "traced",
        "d_hi": "traced",
        "d_score": "traced",
        "d_out": "traced",
        "d_lazy": "traced",
        "gossip_factor": "traced",
        "history_gossip": "threaded",
        "history_length": "threaded",
        "backoff_ticks": "traced",
        "fanout_ttl_ticks": "traced",
        "gossip_retransmission": {"xla": "traced",
                                  "kernel": "threaded"},
        # statically-enforced IHAVE invariants: build-time rejection in
        # make_gossip_sim / __post_init__, never run-time truncation
        "max_ihave_length": "build-time",
        "max_ihave_messages": "build-time",
        "binomial_gossip_sampling": "threaded",
    }

    def __post_init__(self):
        offs = np.asarray(self.offsets, dtype=np.int64)
        if len(offs) == 0 or len(set(offs.tolist())) != len(offs):
            raise ValueError("offsets must be distinct and non-empty")
        if len(offs) > 32:
            raise ValueError("at most 32 candidates (uint32 bitmasks)")
        if not all((-o) in set(offs.tolist()) for o in offs.tolist()):
            raise ValueError("offsets must be closed under negation")
        if self.paired_topics and (self.n_topics < 2
                                   or self.n_topics % 2):
            raise ValueError("paired_topics needs an even n_topics >= 2")
        modulus = (self.n_topics // 2 if self.paired_topics
                   else self.n_topics)
        if any(o % modulus for o in offs.tolist()):
            raise ValueError(
                "offsets must be multiples of n_topics"
                + ("/2 (paired mode)" if self.paired_topics else ""))
        if not (self.d_lo <= self.d <= self.d_hi):
            raise ValueError("need Dlo <= D <= Dhi (gossipsub.go:33-35)")
        if self.d_score > self.d:
            raise ValueError("need Dscore <= D")
        if self.d_out >= self.d_lo or self.d_out > self.d // 2:
            raise ValueError(
                "need Dout < Dlo and Dout <= D/2 (gossipsub.go:266-272)")
        if self.d_hi >= len(offs):
            raise ValueError("need C > Dhi candidate columns")
        if self.history_gossip > self.history_length:
            raise ValueError(
                "need HistoryGossip <= HistoryLength (gossipsub.go:47)")
        if self.gossip_retransmission < 1:
            raise ValueError("gossip_retransmission must be >= 1")
        if not (1 <= self.backoff_ticks <= 32767):
            raise ValueError(
                "backoff_ticks must fit int16 remaining-tick storage")
        if self.max_ihave_length < 1 or self.max_ihave_messages < 1:
            raise ValueError("IHAVE caps must be >= 1")

    @property
    def n_candidates(self) -> int:
        return len(self.offsets)

    @property
    def cinv(self) -> tuple[int, ...]:
        """cinv[c] = bit of the negated offset (the partner's view of
        edge bit c)."""
        idx = {o: i for i, o in enumerate(self.offsets)}
        return tuple(idx[-o] for o in self.offsets)

    @property
    def outbound_mask(self) -> int:
        """Static bitmask of outbound candidate bits (we dial positive
        offsets; the reference tracks dial direction per conn,
        gossipsub.go:1376-1435)."""
        return sum(1 << c for c, o in enumerate(self.offsets) if o > 0)


def _pack_bits_pm_np(bits: np.ndarray) -> np.ndarray:
    """Host-side twin of ops.graph.pack_bits_pm (bool [N, M] -> uint32
    [W, N]): pack BEFORE the host->device transfer so a 1M-peer sim
    ships W words per peer instead of M bools (32x less tunnel
    traffic; same values)."""
    n, m = bits.shape
    w = (m + WORD_BITS - 1) // WORD_BITS
    pad = w * WORD_BITS - m
    if pad:
        bits = np.concatenate(
            [bits, np.zeros((n, pad), dtype=bits.dtype)], axis=-1)
    # np.packbits -> EXPLICITLY little-endian u32 view: the packed byte
    # stream is little-endian by construction (bitorder="little"), so
    # the word view must be '<u4' — a native-endian view would silently
    # scramble bit positions on a big-endian host.  astype then converts
    # values (not bytes) to the native uint32 jax expects; on
    # little-endian hosts it is a no-op alias.
    # tests/test_gossipsub_sim.py::test_pack_bits_pm_np_matches_device
    # pins this against ops.graph.pack_bits_pm.
    words = np.packbits(bits.astype(np.uint8), axis=-1,
                        bitorder="little").view("<u4").astype(
                            np.uint32, copy=False)
    return np.ascontiguousarray(words.T)


def _to_device(a: np.ndarray) -> jnp.ndarray:
    """Move a host-built array to device — but materialize all-zero
    arrays directly on device instead of transferring them.

    The no-attack configs (app_score=None, unique IPs, no sybils) make
    every [C, N] static-score view identically zero; at 1M peers that
    is ~200 MB of zeros per sim, and bulk host->device transfers are
    exactly what stresses the axon tunnel's relayed transport
    (PERF_NOTES operational notes).  Value-identical either way.
    """
    if not a.any():
        return jnp.zeros(a.shape, dtype=a.dtype)
    return jnp.asarray(a)


def make_gossip_offsets(n_topics: int, n_candidates: int, n_peers: int,
                        seed: int = 0,
                        paired: bool = False) -> tuple[int, ...]:
    """Random ± paired circulant offsets ≡ 0 (mod n_topics): each residue
    class (= topic) forms an independent random circulant candidate graph
    (expander — same locally-tree-like spread as the reference test
    harness's random topologies, floodsub_test.go:65-81).

    With ``paired=True`` the offsets are multiples of n_topics/2 for the
    overlapping two-topics-per-peer mode (GossipSimConfig.paired_topics)."""
    modulus = n_topics // 2 if paired else n_topics
    offs = make_circulant_offsets(modulus, n_candidates, n_peers,
                                  seed=seed)
    return tuple(int(o) for o in offs)


@dataclass(frozen=True)
class ScoreSimConfig:
    """Static v1.1 hardening config: the peer-score formula (P1..P7,
    score.go:256-333), thresholds (score_params.go:12-32), and the sybil
    behavior toggles for adversarial runs (gossipsub_spam_test.go).

    Decays are per-tick factors (one tick = one heartbeat); the reference's
    ScoreParameterDecay math (score_params.go:277-287) converts wall-clock
    decays to this form.  Weights follow the reference's sign invariants
    (score_params.go:34-268): P1/P2/P5 >= 0, P3/P3b/P4/P6/P7 <= 0.
    """

    topic_weight: float = 1.0
    # cap on the summed per-topic contribution (P1..P4 across topics,
    # before P5..P7 are added) — score.go:256-268 TopicScoreCap.
    # 0 disables, like the reference default.
    topic_score_cap: float = 0.0
    # P1: time in mesh (capped ramp)
    time_in_mesh_weight: float = 0.1
    time_in_mesh_quantum: int = 1           # ticks per unit
    time_in_mesh_cap: float = 10.0
    # P2: first message deliveries (decaying, capped counter)
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.9
    first_message_deliveries_cap: float = 50.0
    # P3: mesh message delivery deficit (squared, below threshold, only
    # after the edge has been in the mesh for the activation window).
    # Weight defaults to 0 (disabled): like the reference — which ships
    # no default score params at all — P3's threshold must be calibrated
    # to the topic's expected message rate, or quiet meshes churn.
    mesh_message_deliveries_weight: float = 0.0
    mesh_message_deliveries_decay: float = 0.9
    mesh_message_deliveries_cap: float = 20.0
    mesh_message_deliveries_threshold: float = 1.0
    mesh_message_deliveries_activation: int = 5   # ticks
    # P3b: sticky failure penalty applied at prune time
    mesh_failure_penalty_weight: float = 0.0
    mesh_failure_penalty_decay: float = 0.9
    # P4: invalid message deliveries (squared)
    invalid_message_deliveries_weight: float = -10.0
    invalid_message_deliveries_decay: float = 0.95
    # P5: application-specific (per-peer value supplied in params)
    app_specific_weight: float = 1.0
    # P6: IP colocation (squared surplus over threshold)
    ip_colocation_factor_weight: float = -5.0
    ip_colocation_factor_threshold: float = 1.0
    # P7: behavioural penalty (squared surplus; broken IWANT promises +
    # GRAFT-during-backoff violations, gossipsub.go:747-765,1566-1571)
    behaviour_penalty_weight: float = -10.0
    behaviour_penalty_decay: float = 0.9
    behaviour_penalty_threshold: float = 0.0
    decay_to_zero: float = 0.01
    # thresholds (PeerScoreThresholds, score_params.go:12-32)
    gossip_threshold: float = -10.0
    publish_threshold: float = -50.0
    graylist_threshold: float = -80.0
    opportunistic_graft_threshold: float = 1.0
    opportunistic_graft_ticks: int = 60
    opportunistic_graft_peers: int = 2
    # router options
    flood_publish: bool = False             # WithFloodPublish
    # sybil behavior toggles (peers flagged sybil in params)
    sybil_ihave_spam: bool = False          # broken-promise IWANT flood
    sybil_graft_flood: bool = False         # re-GRAFT while backed off
    # IWANT-flood (gossipsub_spam_test.go:24): sybils re-request the
    # full advertised window from every candidate every tick; victims
    # serve until the per-edge retransmission budget saturates
    # (mcache.go:66-80 + gossipsub.go:690-693)
    sybil_iwant_spam: bool = False
    # Eclipse formation (round 11; "GossipSub: Attack-Resilient
    # Message Propagation" §eclipse): peers flagged in the sim's
    # ``eclipse_sybil`` array coordinate GRAFT pressure on a VICTIM
    # set (``eclipse_victim``) — every tick they GRAFT at every
    # subscribed victim candidate, ignoring their own backoff, and
    # forward NOTHING once inside (silent mesh occupation starves the
    # victim).  Defense path: re-grafting during backoff accrues P7
    # at the victim, the penalty squares into a negative score, and
    # the victim's maintenance drops + graylists the attacker — the
    # takeover bound tests/test_attacks.py pins.
    sybil_eclipse: bool = False
    # Byzantine id-preserving payload mutation (round 11): peers
    # flagged in ``byzantine`` corrupt the CONTENT of every copy they
    # relay or serve (the id is preserved — the copy reaches the
    # receiver's validator and fails).  A mutated copy is rejected:
    # it accrues the per-edge P4 invalid-delivery penalty and NEVER
    # enters possession, so the receiver can still acquire the honest
    # bytes from another edge (validation.go:274-351 semantics).
    byzantine_mutation: bool = False
    # counter storage dtype: bfloat16 halves the dominant HBM traffic of
    # the v1.1 step (6 [C, N] counters r+w per tick); the counters are
    # small decaying sums where ~3 significant digits is ample.  All
    # arithmetic still runs in f32 (cast on read, cast on write).
    counter_dtype: str = "bfloat16"

    # Machine-readable thread-or-refuse contract (round 11 — verified
    # by tools/graftlint/contracts.py like GossipSimConfig's): every
    # score knob must provably reach the compiled step on both
    # execution paths, or be provably refused.  The P3/P3b family is
    # kernel-refused (the fused kernel elides the split-loop
    # provenance P3 needs), as is byzantine mutation (per-edge content
    # corruption needs the per-edge receive loops).
    PATHS: ClassVar[tuple[str, ...]] = ("xla", "kernel")
    _KERNEL_REFUSED: ClassVar[dict[str, str]] = {
        "xla": "threaded", "kernel": "refused"}
    CONTRACT: ClassVar[dict[str, object]] = {
        "topic_weight": "threaded",
        "topic_score_cap": "threaded",
        "time_in_mesh_weight": "threaded",
        "time_in_mesh_quantum": "threaded",
        "time_in_mesh_cap": "threaded",
        "first_message_deliveries_weight": "threaded",
        "first_message_deliveries_decay": "threaded",
        "first_message_deliveries_cap": "threaded",
        "mesh_message_deliveries_weight": _KERNEL_REFUSED,
        "mesh_message_deliveries_decay": _KERNEL_REFUSED,
        "mesh_message_deliveries_cap": _KERNEL_REFUSED,
        "mesh_message_deliveries_threshold": _KERNEL_REFUSED,
        "mesh_message_deliveries_activation": _KERNEL_REFUSED,
        "mesh_failure_penalty_weight": _KERNEL_REFUSED,
        "mesh_failure_penalty_decay": _KERNEL_REFUSED,
        "invalid_message_deliveries_weight": "threaded",
        "invalid_message_deliveries_decay": "threaded",
        "app_specific_weight": "threaded",
        "ip_colocation_factor_weight": "threaded",
        "ip_colocation_factor_threshold": "threaded",
        "behaviour_penalty_weight": "threaded",
        "behaviour_penalty_decay": "threaded",
        "behaviour_penalty_threshold": "threaded",
        "decay_to_zero": "threaded",
        "gossip_threshold": "threaded",
        "publish_threshold": "threaded",
        "graylist_threshold": "threaded",
        "opportunistic_graft_threshold": "threaded",
        "opportunistic_graft_ticks": "threaded",
        "opportunistic_graft_peers": "threaded",
        "flood_publish": "threaded",
        "sybil_ihave_spam": "threaded",
        "sybil_graft_flood": "threaded",
        "sybil_iwant_spam": "threaded",
        "sybil_eclipse": "threaded",
        "byzantine_mutation": _KERNEL_REFUSED,
        "counter_dtype": "threaded",
    }

    @property
    def bp_dtype(self) -> str:
        """behaviour_penalty storage dtype.

        P7 increments are at most +2 per edge-tick (a backoff violation
        plus a broken promise), so the decaying counter's worst-case
        steady state is 2/(1-decay).  When that stays far below bf16's
        +1-absorption point (256) the counter stores in counter_dtype
        like the others; configs with very slow decay keep f32 (the
        stick-at-256 hazard the round-1 note recorded)."""
        if jnp.dtype(self.counter_dtype) == jnp.float32:
            return "float32"
        if 2.0 / (1.0 - self.behaviour_penalty_decay) < 128.0:
            return self.counter_dtype
        return "float32"

    @property
    def track_p3(self) -> bool:
        """P3/P3b bookkeeping (mesh-delivery deficits) is skipped entirely
        when both weights are 0 — the shipped default, mirroring that the
        reference requires explicit per-topic P3 calibration."""
        return (self.mesh_message_deliveries_weight != 0
                or self.mesh_failure_penalty_weight != 0)

    def validate(self) -> None:
        """The reference's sign/range invariants are free tests
        (score_params.go:34-268)."""
        if self.topic_weight < 0:
            raise ValueError("topic_weight must be >= 0")
        for name in ("time_in_mesh_weight", "first_message_deliveries_weight",
                     "app_specific_weight"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        for name in ("mesh_message_deliveries_weight",
                     "mesh_failure_penalty_weight",
                     "invalid_message_deliveries_weight",
                     "ip_colocation_factor_weight",
                     "behaviour_penalty_weight"):
            if getattr(self, name) > 0:
                raise ValueError(f"{name} must be <= 0")
        for name in ("first_message_deliveries_decay",
                     "mesh_message_deliveries_decay",
                     "mesh_failure_penalty_decay",
                     "invalid_message_deliveries_decay",
                     "behaviour_penalty_decay"):
            d = getattr(self, name)
            if not (0 < d < 1):
                raise ValueError(f"{name} must be in (0, 1)")
        if not (self.graylist_threshold <= self.publish_threshold
                <= self.gossip_threshold <= 0):
            raise ValueError(
                "need graylist <= publish <= gossip threshold <= 0")


# --------------------------------------------------------------------------
# Pytrees.  Candidate masks are packed uint32 [N]; dense per-edge numeric
# state (score counters, backoff ticks) is [C, N] peer-minor.
# --------------------------------------------------------------------------


#: the defense parameters the attack×defense tournament sweeps as DATA
#: (traced operands instead of baked constants), in ScoreKnobs field
#: order.  Everything else in ScoreSimConfig stays compile-time.
SCORE_KNOB_FIELDS = ("invalid_message_deliveries_weight",
                     "behaviour_penalty_weight",
                     "graylist_threshold", "gossip_threshold")


@struct.dataclass
class ScoreKnobs:
    """Traced score-parameter overrides (round 11): the four defense
    knobs the attack tournament sweeps ride the params as f32 SCALAR
    LEAVES, so ``vmap``/``stack_trees`` batches advance replicas with
    HETEROGENEOUS defense settings in one dispatch — the mini config-
    as-data step toward ROADMAP direction 2.  ``None`` (the default)
    bakes the ScoreSimConfig values as before, bit-identically.  XLA
    path only: the pallas kernel emits next-tick gates in-kernel from
    baked thresholds (kernel_capability refuses knobbed params)."""

    invalid_message_deliveries_weight: jnp.ndarray  # f32 [] (<= 0)
    behaviour_penalty_weight: jnp.ndarray           # f32 [] (<= 0)
    graylist_threshold: jnp.ndarray                 # f32 []
    gossip_threshold: jnp.ndarray                   # f32 []


@struct.dataclass
class GossipParams:
    """Per-simulation device arrays (dynamic operands of the jitted step).

    The v1.1 fields (None when scoring is off) carry per-CANDIDATE views of
    static per-peer attributes: row c of column p describes peer p+o_c.
    """

    subscribed: jnp.ndarray      # bool [N]: has a local subscription
    cand_sub_bits: jnp.ndarray   # uint32 [N]: bit c = candidate subscribed
    origin_words: jnp.ndarray    # uint32 [W, N]: bit m set at origin[m]
    deliver_words: jnp.ndarray   # uint32 [W, N]: msg m counts as delivery
    publish_tick: jnp.ndarray    # int32 [M]
    # paired-topic mode: bit m set iff msg m's topic is peer p's SECOND
    # topic slot (so it forwards on mesh_b rather than mesh)
    slot_b_words: jnp.ndarray | None = None   # uint32 [W, N]
    invalid_words: jnp.ndarray | None = None  # uint32 [W]: msg fails validation
    cand_app_score: jnp.ndarray | None = None # f32 [C, N]: P5 of candidate
    cand_colo_excess: jnp.ndarray | None = None  # f32 [C, N]: P6 surplus
    # P5 + P6 are static per-run, so their weighted sum is precomputed
    # once (make_gossip_sim) instead of re-deriving colo² + the two
    # multiply-adds from 128 MB of f32 inputs every tick
    cand_static_score: jnp.ndarray | None = None  # f32 [C, N]
    # bake-time (app_specific_weight, ip_colocation_factor_weight):
    # compute_scores only trusts cand_static_score when the config it is
    # called with still matches these, else it falls back to the
    # component path (a re-weighted ScoreSimConfig must not silently
    # read a stale baked term)
    static_score_weights: tuple | None = struct.field(
        pytree_node=False, default=None)
    # True when the baked static term is identically zero (no app
    # scores, no shared IPs — the flagship bench shape): the step then
    # ELIDES the [C, N] f32 read entirely (64 MB/tick at 1M peers) on
    # both the XLA and kernel paths.  Value-identical: x + 0.0 == x for
    # every finite x, and no comparison downstream distinguishes ±0.
    static_score_zero: bool = struct.field(pytree_node=False,
                                           default=False)
    # true peer count when the peer axis is padded for the pallas step
    # (make_gossip_sim pad_to_block); None = unpadded.  Peers >= n_true
    # are inert: unsubscribed, candidate-invisible, and the circulant
    # views wrap at n_true, so they can neither send nor retain state.
    n_true: int | None = struct.field(pytree_node=False, default=None)
    cand_sybil: jnp.ndarray | None = None     # bool [C, N]: candidate is sybil
    sybil: jnp.ndarray | None = None          # bool [N]
    # per-IP shared fate at the gater (peer_gater.go:119-151): word at
    # [c, p] has bit c' set iff candidates p+o_c and p+o_c' share a
    # source IP.  Built only when some IP is actually shared, so
    # unique-IP sims (the common case) skip the grouping pass entirely.
    cand_same_ip: jnp.ndarray | None = None   # uint32 [C, N]
    # peers that advertise gossip but withhold the payload (broken
    # IWANT promises) WITHOUT being flagged sybil — stealthy spammers.
    # P7 is behavioral (derived from advertised-vs-delivered traffic,
    # gossip_tracer.go:48-153), so these accrue it like flagged ones.
    promise_break: jnp.ndarray | None = None  # bool [N]
    # mixed-protocol support (None = homogeneous gossipsub network):
    # floodsub-protocol peers are always flooded and never mesh/gossip
    # (feature negotiation, gossipsub_feat.go:11-52, gossipsub.go:969-974)
    flood_proto: jnp.ndarray | None = None       # bool [N]
    cand_flood_bits: jnp.ndarray | None = None   # uint32 [N]
    # operator-pinned DIRECT peers, per edge (bit c = candidate p+o_c
    # is a direct peer of p; symmetric).  Direct edges always receive
    # eager forwards (gossipsub.go:945-950), bypass the graylist/gater
    # on both payload and control (AcceptFrom, gossipsub.go:578-586),
    # and never enter meshes — GRAFT at a direct edge is rejected
    # (gossipsub.go:737-745).  The sim's always-on edge is the analog
    # of the periodic directConnect reconnection (gossipsub.go:1594).
    cand_direct: jnp.ndarray | None = None       # uint32 [N]
    # compiled fault schedule (models/faults.py): per-tick churn/link-
    # loss/partition masks, computed inside the scan.  Honored by both
    # execution paths: the XLA rolls mask directly, the pallas kernel
    # threads the alive/link words through its VMEM pass.
    faults: _faults.FaultParams | None = None
    # -- round-11 attack surface (arrays, so stacked replicas vary the
    # formation per replica under ONE compiled step) ---------------------
    # eclipse formation (ScoreSimConfig.sybil_eclipse): the attackers
    # and their victim set.  cand_victim_bits[p] bit c = candidate
    # p+o_c is a victim.
    eclipse_sybil: jnp.ndarray | None = None      # bool [N]
    eclipse_victim: jnp.ndarray | None = None     # bool [N]
    cand_victim_bits: jnp.ndarray | None = None   # uint32 [N]
    # Byzantine payload mutators (ScoreSimConfig.byzantine_mutation):
    # cand_byz[p] bit c = candidate p+o_c corrupts what it relays.
    byzantine: jnp.ndarray | None = None          # bool [N]
    cand_byz: jnp.ndarray | None = None           # uint32 [N]
    # traced defense-knob overrides (attack tournament); None = baked
    score_knobs: ScoreKnobs | None = None
    # -- round-12 config-as-data (models/knobs.py): the full liftable
    # protocol-parameter surface as traced scalar leaves — degree
    # family, gossip_factor, retransmission budget, backoff/fanout-TTL
    # ticks, plus the ScoreKnobs defense sub-tree folded in.  None =
    # every parameter baked from the static config, bit-identically.
    sim_knobs: _knobs.SimKnobs | None = None
    # -- round-13 event-driven time (models/delays.py): per-edge delay
    # lines + jitter.  base/jitter ride as TRACED i32 leaves (the
    # delay_base / delay_jitter knobs sweep them recompile-free); the
    # K-slot depth is static and sizes the GossipState delay lines.
    # None = the exact one-tick-one-hop pre-delay step.
    delays: _delays.DelayParams | None = None


@struct.dataclass
class ScoreState:
    """Per-edge v1.1 reputation counters: row c, column p = p's view of
    candidate p+o_c (the score engine's per-(peer, topic) stats,
    score.go:95-118, densified on the candidate axis)."""

    time_in_mesh: jnp.ndarray        # int16 [C, N] ticks since graft (P1;
    #   exact integer count — bf16 would silently stick at 256 — saturated
    #   at 32766)
    first_deliveries: jnp.ndarray    # f32 [C, N] decaying counter (P2)
    # P3/P3b state exists only when the config tracks it
    # (ScoreSimConfig.track_p3) — None otherwise, so the scan carry
    # doesn't thread two dead [C, N] arrays per tick
    mesh_deliveries: jnp.ndarray | None      # f32 [C, N] (P3)
    mesh_failure_penalty: jnp.ndarray | None  # f32 [C, N] deficit² (P3b)
    invalid_deliveries: jnp.ndarray  # f32 [C, N] decaying counter (P4)
    behaviour_penalty: jnp.ndarray   # [C, N] decaying counter (P7;
    #   dtype = ScoreSimConfig.bp_dtype)
    # paired-topic mode only: P1 for the SECOND topic slot's mesh (the
    # other counters aggregate across the two equal-weight topics; time
    # in mesh is per-topic because the meshes differ)
    time_in_mesh_b: jnp.ndarray | None = None  # int16 [C, N]


@struct.dataclass
class GossipState:
    mesh: jnp.ndarray        # uint32 [N]  mesh membership bitmask
    fanout: jnp.ndarray      # uint32 [N]  publish-without-join bitmask
    last_pub: jnp.ndarray    # int32 [N]    last publish tick (fanout TTL)
    backoff: jnp.ndarray     # int32 [C, N] no re-GRAFT until this tick
    have: jnp.ndarray        # uint32 [W, N]
    recent: jnp.ndarray      # uint32 [Hg, W, N] newly-acquired ring (mcache)
    first_tick: jnp.ndarray  # int16 [W, 32, N] or None
    scores: ScoreState | None  # None when v1.1 scoring is disabled
    key: jax.Array           # PRNG key
    tick: jnp.ndarray        # int32 scalar
    # Gossip-repair abuse-bound state (ALWAYS allocated when scoring is
    # on, matching the reference's unconditional per-message
    # transmission tally, mcache.go:66-80): iwant_serves[c, p] counts
    # the ids peer p has been SERVED (pulled) over its candidate-c edge,
    # decayed as mcache entries expire — i.e. the partner's per-edge
    # retransmission ledger for p, stored at the requester so the hot
    # path reuses the receiver-side provenance popcounts (no extra
    # rolls).  Honest edges stay far below the GossipRetransmission x
    # window budget (each id is news over an edge at most once); an
    # IWANT-flooding sybil's rows saturate at it.
    iwant_serves: jnp.ndarray | None = None  # int16 [C, N]
    # paired-topic mode: the SECOND topic slot's mesh and backoff (each
    # topic keeps its own mesh + per-edge backoff, gossipsub.go:135)
    mesh_b: jnp.ndarray | None = None        # uint32 [N]
    backoff_b: jnp.ndarray | None = None     # int32 [C, N]
    # PX-driven candidate refresh (px_candidates): the ACTIVE subset of
    # the candidate pool a peer currently knows/dials.  PRUNE receipt
    # rotates the pruned bit out and a fresh candidate in — the sim's
    # analog of PRUNE-carried peer exchange feeding the connector
    # (gossipsub.go:856-937): the static pool models the addresses PX
    # could hand out, the active mask models which are currently held.
    active: jnp.ndarray | None = None        # uint32 [N]
    # pipelined score gates: THIS tick's packed threshold/gater/backoff
    # gate words, emitted at the END of the previous tick while the
    # updated counters were still in registers (or in the pallas
    # kernel's VMEM) — so the tick prologue never re-reads the [C, N]
    # counter state.  Bit-identical to recomputing at tick start: the
    # gates are pure functions of (counters, backoff, mesh) and the
    # emission applies the same storage rounding the prologue would
    # read back.  A TUPLE of separate [N] words, NOT a stacked [G, N]
    # array: slicing row g of a [G, N] T(8,128) array reads whole
    # sublane tiles and discards (G-1)/G of the bandwidth (measured
    # ~160 us/row at 1M — the same penalty PERF_NOTES records for
    # row-wise counter ops).  Order (see compute_gates): scored
    # (accept, gossip, publish, nonneg, payload, targets,
    # backoff(, backoff_b)); unscored (targets, backoff(, backoff_b)).
    gates: tuple | None = None               # tuple of uint32 [N]
    # fingerprint of the (cfg, score_cfg) the carried gates were emitted
    # under (gates_fingerprint): a same-SHAPE but different-threshold
    # config would otherwise silently act on the old config's gates for
    # its first tick — the row-count guard can't see value changes.
    # Static aux data (not a leaf): never checkpointed, restored from
    # the template.
    gates_fp: int | None = struct.field(pytree_node=False, default=None)
    # in-scan invariant-checker carry (models/invariants.py, round 11):
    # cumulative violation bitmask + first violating tick.  None (the
    # default) keeps the pytree identical to the pre-invariant state;
    # invariants.attach(state) arms them.
    inv_viol: jnp.ndarray | None = None      # uint32 []
    inv_first: jnp.ndarray | None = None     # int32 []
    # round-13 event-driven time (models/delays.py): the K-slot
    # circular delay lines carried through the scan.  pay_line holds
    # in-flight payload/gossip words per receiving edge (slot s, edge
    # bit j, word w); ctrl_line holds the packed in-flight control
    # words (rows: GRAFT, PRUNE, retraction(, broken-promise advert));
    # gsp_line is the gossip-class twin of pay_line, allocated only
    # for the split execution paths (track_p3 / force_split) that need
    # mesh-vs-gossip arrival provenance.  All None when delays are off
    # — the pytree stays identical to the pre-delay state.
    pay_line: jnp.ndarray | None = None      # uint32 [K, C, W, N]
    ctrl_line: jnp.ndarray | None = None     # uint32 [K, R, N]
    gsp_line: jnp.ndarray | None = None      # uint32 [K, C, W, N]
    # round-19 delay-armed telemetry counters: the IHAVE advert words
    # in flight, observer-only (possession never reads it) — the
    # iwant_requested/iwant_rpcs estimators need the advert arrival
    # view, which the fused pay_line cannot reconstruct.  Allocated by
    # make_gossip_sim(..., delays_counters=True); None otherwise.
    adv_line: jnp.ndarray | None = None      # uint32 [K, C, W, N]
    # round-20 delay-armed rpc_probe (the lifted registry hole): the
    # three send-class attempt masks in flight (rows: eager-forward,
    # IHAVE advert, publish-flood), observer-only — the probe
    # snapshot's arrival leaves dequeue from it so the exporter can
    # place RECVs at the true arrival tick.  Possession never reads
    # it.  Allocated by make_gossip_sim(..., delays_probe=True).
    probe_line: jnp.ndarray | None = None    # uint32 [K, 3, N]


def make_gossip_sim(cfg: GossipSimConfig, subs: np.ndarray,
                    msg_topic: np.ndarray, msg_origin: np.ndarray,
                    msg_publish_tick: np.ndarray, seed: int = 0,
                    track_first_tick: bool = True,
                    score_cfg: ScoreSimConfig | None = None,
                    app_score: np.ndarray | None = None,
                    peer_ip: np.ndarray | None = None,
                    sybil: np.ndarray | None = None,
                    msg_invalid: np.ndarray | None = None,
                    flood_proto: np.ndarray | None = None,
                    promise_break: np.ndarray | None = None,
                    px_candidates: int | None = None,
                    direct_edges: np.ndarray | None = None,
                    pad_to_block: int | None = None,
                    fault_schedule: _faults.FaultSchedule | None = None,
                    eclipse_sybil: np.ndarray | None = None,
                    eclipse_victim: np.ndarray | None = None,
                    byzantine: np.ndarray | None = None,
                    score_knobs: dict | None = None,
                    sim_knobs: dict | None = None,
                    delays: _delays.DelayConfig | None = None,
                    delays_split: bool = False,
                    delays_counters: bool = False,
                    delays_probe: bool = False):
    """Build (params, state).  subs: bool [N, T] — but each peer may only
    subscribe to its residue-class topic (circulant classes are closed, so
    cross-class subscriptions would never receive anything).

    With score_cfg, the v1.1 reputation layer is enabled:
    - app_score [N] f32: P5 application-specific score per peer
    - peer_ip [N] int: IP assignment; peers sharing an IP accrue the P6
      colocation penalty (sybils behind one address share fate,
      score.go:967-1007)
    - sybil [N] bool: peers running the configured attack behaviors
    - msg_invalid [M] bool: messages that fail validation (P4 + no
      forwarding, validation.go:274-351)

    flood_proto [N] bool marks peers speaking /floodsub/1.0.0 in a mixed
    network: they flood everything they hold to all subscribed candidates
    and are flooded by gossipsub peers, but never join meshes or exchange
    gossip (gossipsub_feat.go:11-52, gossipsub.go:969-974).

    fault_schedule (models/faults.py) injects churn/link-loss/partition
    events into the step, on either execution path (the pallas kernel
    threads the per-tick alive/link mask words through its VMEM pass).
    The schedule is sized to the TRUE peer count; with pad_to_block
    the pad lanes ride as alive-with-links-up.  ``cold_restart``
    schedules additionally clear a rejoining peer's possession +
    mcache at the rejoin tick (both paths — the clear is in the
    shared prologue).

    Round-11 attack arrays (all require score_cfg):
    - eclipse_sybil [N] bool + eclipse_victim [N] bool: the eclipse
      formation's attackers and targets (live when
      score_cfg.sybil_eclipse).
    - byzantine [N] bool: id-preserving payload mutators (live when
      score_cfg.byzantine_mutation).
    - score_knobs: dict over SCORE_KNOB_FIELDS — traced defense-knob
      overrides for the attack×defense tournament (missing keys fall
      back to the score_cfg value; sign/order validated here).  Both
      execution paths since round 12 (the kernel takes them as SMEM
      scalars).

    sim_knobs (round 12, models/knobs.py) lifts the full liftable
    protocol surface to traced operands: a dict mixing protocol knobs
    (SIM_KNOB_FIELDS — d family, gossip_factor, backoff/fanout ticks,
    gossip_retransmission), ScoreKnobs defense fields (folded into the
    SimKnobs.score sub-tree; requires score_cfg), and the fault knob
    ``drop_prob`` (overrides the compiled FaultParams link-drop rate;
    requires a fault_schedule whose drop_prob is nonzero so the link
    code path compiles in — knob value 0.0 is then a value-level
    no-drop).  Shape-bearing fields raise KnobStaticFieldError by
    name.  Missing keys take the config's own values, bit-identically
    to the baked step.  Mutually exclusive with ``score_knobs`` (one
    override surface per sim).

    delays (round 13, models/delays.py) arms event-driven time: a
    DelayConfig compiles to traced base/jitter scalars on the params
    plus the K-slot circular delay lines on the state, so payload/
    gossip/control transfers take heterogeneous integer ticks instead
    of exactly one.  ``DelayConfig(base=1, jitter=0, k_slots=1)`` is
    bit-identical to the pre-delay step (pinned); the ``delay_base`` /
    ``delay_jitter`` sim_knobs sweep the heartbeat/RTT ratio
    recompile-free (the k_slots depth is shape-bearing and rejected
    by name).  ``delays_split=True`` additionally allocates the
    gossip-class delay line the SPLIT execution paths (track_p3 /
    force_split builds of make_gossip_step) need for mesh-vs-gossip
    arrival provenance.  ``delays_counters=True`` allocates the
    advert + gossip observer lines delay-armed telemetry counters
    dequeue (round 19); ``delays_probe=True`` allocates the [K, 3, N]
    probe line delay-armed ``rpc_probe`` builds dequeue their
    ``arr_*`` arrival masks from (round 20).
    """
    n, t = subs.shape
    if t != cfg.n_topics:
        raise ValueError("subs topic dim != cfg.n_topics")
    own_topic = np.arange(n) % cfg.n_topics
    m = len(msg_topic)
    if m > cfg.max_ihave_length:
        # the sim advertises its whole id space in one merged IHAVE per
        # edge per tick; IHAVE truncation above MaxIHaveLength
        # (gossipsub.go:610-672) is not modeled, so the cap is enforced
        # as a static invariant instead of run-time truncation
        raise ValueError(
            f"n_msgs={m} exceeds max_ihave_length="
            f"{cfg.max_ihave_length}: the sim's one-IHAVE-per-edge "
            "advert must fit the reference cap")
    origin_bits = np.zeros((n, m), dtype=bool)
    origin_bits[msg_origin, np.arange(m)] = True
    if cfg.paired_topics:
        # overlapping membership: peer p subscribes to BOTH topics
        # {r, r + T/2}; subs rows must be exactly that pair (or empty
        # for non-participants)
        second = (own_topic + cfg.n_topics // 2) % cfg.n_topics
        pair = ((np.arange(t)[None, :] == own_topic[:, None])
                | (np.arange(t)[None, :] == second[:, None]))
        if (subs & ~pair).any():
            raise ValueError(
                "paired mode: peers may only subscribe to "
                "{p mod T, p mod T + T/2}")
        both = subs[np.arange(n), own_topic] & subs[np.arange(n), second]
        neither = ~(subs[np.arange(n), own_topic]
                    | subs[np.arange(n), second])
        if not (both | neither).all():
            raise ValueError("paired mode: subscribe to both topics of "
                             "the pair, or neither")
        subscribed = both
        org_t = own_topic[msg_origin]
        org_t2 = second[msg_origin]
        if (~((org_t == msg_topic) | (org_t2 == msg_topic))).any():
            raise ValueError(
                "msg origin must subscribe to the message topic")
        deliver_bits = subscribed[:, None] & (
            (own_topic[:, None] == msg_topic[None, :])
            | (second[:, None] == msg_topic[None, :]))
        # slot-B classification: msg m rides peer p's SECOND topic slot
        slot_b_bits = (second[:, None] == msg_topic[None, :])
    else:
        cross = subs & ~(np.arange(t)[None, :] == own_topic[:, None])
        if cross.any():
            raise ValueError("peers may only subscribe to topic (p mod T)")
        subscribed = subs[np.arange(n), own_topic]
        if ((msg_origin % cfg.n_topics) != msg_topic).any():
            raise ValueError(
                "msg origin must be in the topic's residue class")
        deliver_bits = subscribed[:, None] & (own_topic[:, None]
                                              == msg_topic[None, :])
        slot_b_bits = None

    def cand_view(per_peer):
        """Per-candidate view: out[c, p] = per_peer[p + o_c]."""
        return np.stack([np.roll(per_peer, -o) for o in cfg.offsets], axis=0)

    def cand_bits(per_peer_bool):
        """Packed per-candidate view: uint32 [N], bit c set iff
        per_peer[p + o_c]."""
        out = np.zeros(n, dtype=np.uint32)
        for c, o in enumerate(cfg.offsets):
            out |= np.roll(per_peer_bool, -o).astype(np.uint32) << c
        return out

    # optional peer-axis padding for the pallas step (its grid needs
    # n % block == 0 with a 128-aligned block, which 10^6-style peer
    # counts never satisfy).  Pad peers are inert: unsubscribed, absent
    # from every candidate mask, and the kernel's circulant views wrap
    # at the TRUE n — they can neither send nor be counted.
    n_pad = n if pad_to_block is None else -(-n // pad_to_block
                                             ) * pad_to_block

    def padl(a, fill=0):
        """Pad the LAST axis (peer-minor arrays) from n to n_pad."""
        if n_pad == n:
            return a
        return np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, n_pad - n)],
                      constant_values=fill)

    def pad0(a, fill=0):
        """Pad axis 0 (peer-major arrays) from n to n_pad."""
        if n_pad == n:
            return a
        return np.pad(a, [(0, n_pad - n)] + [(0, 0)] * (a.ndim - 1),
                      constant_values=fill)

    kw = {}
    if score_cfg is not None:
        score_cfg.validate()
        app = (np.zeros(n, dtype=np.float32) if app_score is None
               else np.asarray(app_score, dtype=np.float32))
        syb = (np.zeros(n, dtype=bool) if sybil is None
               else np.asarray(sybil, dtype=bool))
        if peer_ip is None:
            peer_ip = np.arange(n)  # everyone on their own address
        _, ip_idx = np.unique(np.asarray(peer_ip), return_inverse=True)
        colo_count = np.bincount(ip_idx)[ip_idx].astype(np.float32)
        colo_excess = np.maximum(
            0.0, colo_count - score_cfg.ip_colocation_factor_threshold)
        inv = (np.zeros(m, dtype=bool) if msg_invalid is None
               else np.asarray(msg_invalid, dtype=bool))
        app_v = cand_view(app)
        colo_v = cand_view(colo_excess)
        same_ip = None
        if (colo_count > 1).any():
            # shared addresses exist: build the same-IP sibling masks
            # for the gater's per-IP stat grouping
            ips_v = cand_view(ip_idx)
            same = np.zeros((len(cfg.offsets), n), dtype=np.uint32)
            for c2 in range(len(cfg.offsets)):
                same |= (ips_v == ips_v[c2][None, :]).astype(
                    np.uint32) << c2
            same_ip = jnp.asarray(padl(same))
        kw = dict(
            cand_same_ip=same_ip,
            invalid_words=pack_bits(jnp.asarray(inv)),
            cand_app_score=_to_device(padl(app_v)),
            cand_colo_excess=_to_device(padl(colo_v)),
            cand_static_score=_to_device(padl(
                score_cfg.app_specific_weight * app_v
                + score_cfg.ip_colocation_factor_weight * colo_v * colo_v)),
            static_score_weights=(score_cfg.app_specific_weight,
                                  score_cfg.ip_colocation_factor_weight),
            static_score_zero=bool(not app_v.any()
                                   and not colo_v.any()),
            cand_sybil=_to_device(padl(cand_view(syb))),
            sybil=_to_device(padl(syb)),
        )

    if flood_proto is not None:
        fp = np.asarray(flood_proto, dtype=bool)
        kw.update(flood_proto=jnp.asarray(padl(fp)),
                  cand_flood_bits=jnp.asarray(padl(cand_bits(fp))))

    direct_packed = None
    if direct_edges is not None:
        de = np.asarray(direct_edges, dtype=bool)
        if de.shape != (n, cfg.n_candidates):
            raise ValueError("direct_edges must be bool [N, C]")
        # operators configure both ends (WithDirectPeers on each node,
        # gossipsub.go:338): the edge view must be symmetric —
        # de[p, c] == de[p + o_c, cinv_c] (np.roll(x, -o)[p] = x[p+o])
        for c, o in enumerate(cfg.offsets):
            if not (de[:, c] == np.roll(de[:, cfg.cinv[c]], -o)).all():
                raise ValueError(
                    "direct_edges must be symmetric: peer p's bit c "
                    "and peer p+o_c's bit cinv[c] describe one edge")
        direct_packed = np.zeros(n, dtype=np.uint32)
        for c in range(cfg.n_candidates):
            direct_packed |= de[:, c].astype(np.uint32) << c
        kw.update(cand_direct=jnp.asarray(padl(direct_packed)))

    if promise_break is not None:
        if score_cfg is None:
            raise ValueError("promise_break requires score_cfg (P7)")
        kw.update(promise_break=jnp.asarray(
            padl(np.asarray(promise_break, dtype=bool))))

    if eclipse_sybil is not None or eclipse_victim is not None:
        if score_cfg is None:
            raise ValueError("eclipse_sybil/eclipse_victim require "
                             "score_cfg (the defense under test)")
        if eclipse_sybil is None or eclipse_victim is None:
            raise ValueError("eclipse formations need BOTH "
                             "eclipse_sybil and eclipse_victim")
        es = np.asarray(eclipse_sybil, dtype=bool)
        ev = np.asarray(eclipse_victim, dtype=bool)
        if (es & ev).any():
            raise ValueError(
                "eclipse_sybil and eclipse_victim must be disjoint "
                "(an attacker cannot eclipse itself)")
        kw.update(eclipse_sybil=jnp.asarray(padl(es)),
                  eclipse_victim=jnp.asarray(padl(ev)),
                  cand_victim_bits=jnp.asarray(padl(cand_bits(ev))))

    if byzantine is not None:
        if score_cfg is None:
            raise ValueError(
                "byzantine requires score_cfg (mutated copies feed "
                "the validation-reject P4 path)")
        bz = np.asarray(byzantine, dtype=bool)
        kw.update(byzantine=jnp.asarray(padl(bz)),
                  cand_byz=jnp.asarray(padl(cand_bits(bz))))

    if score_knobs is not None:
        if score_cfg is None:
            raise ValueError("score_knobs require score_cfg")
        unknown = set(score_knobs) - set(SCORE_KNOB_FIELDS)
        if unknown:
            raise ValueError(
                f"score_knobs: unknown knob(s) {sorted(unknown)} — "
                f"sweepable knobs are {SCORE_KNOB_FIELDS}")
        kv = {f: float(score_knobs.get(f, getattr(score_cfg, f)))
              for f in SCORE_KNOB_FIELDS}
        for f in ("invalid_message_deliveries_weight",
                  "behaviour_penalty_weight"):
            if kv[f] > 0:
                raise ValueError(f"score_knobs: {f} must be <= 0")
        if not (kv["graylist_threshold"]
                <= score_cfg.publish_threshold
                <= kv["gossip_threshold"] <= 0):
            raise ValueError(
                "score_knobs: need graylist <= publish (static) <= "
                "gossip threshold <= 0")
        kw.update(score_knobs=ScoreKnobs(
            **{f: jnp.float32(kv[f]) for f in SCORE_KNOB_FIELDS}))

    if fault_schedule is not None:
        # both paths honor fault masks (the pallas kernel threads the
        # per-tick alive/link words through its VMEM pass); the
        # schedule is always sized to the TRUE peer count — pad lanes
        # are appended as alive-with-links-up inside the step
        if fault_schedule.n_peers != n:
            raise ValueError(
                f"fault_schedule.n_peers={fault_schedule.n_peers} != "
                f"sim peer count {n}")
        kw.update(faults=_faults.compile_faults(
            fault_schedule, cfg.offsets, pack_links=True))

    if delays is not None:
        if cfg.paired_topics:
            # named capability gap (graftlint probe-refusal registry):
            # the two-mesh overlay would need per-slot payload and
            # ctrl delay lines plus delayed cross-slot routing
            raise NotImplementedError(_plan.MSG_DELAYS_PAIRED)
        kw.update(delays=_delays.compile_delays(delays))

    if sim_knobs is not None:
        if score_knobs is not None:
            raise ValueError(
                "pass parameter overrides through ONE surface: "
                "sim_knobs (which folds the ScoreKnobs fields in) or "
                "the legacy score_knobs dict, not both")
        proto_kv, score_kv, fault_kv, delay_kv = \
            _knobs.split_knob_overrides(sim_knobs, SCORE_KNOB_FIELDS)
        kw.update(sim_knobs=_knobs.make_sim_knobs(
            cfg, score_cfg, {**proto_kv, **score_kv},
            px_candidates=px_candidates))
        if fault_kv:
            fp0 = kw.get("faults")
            if fp0 is None:
                raise ValueError(
                    "sim_knobs: the drop_prob knob overrides a "
                    "compiled FaultParams leaf — pass a "
                    "fault_schedule alongside it")
            if fp0.drop_prob is None or fp0.drop_prob.ndim != 0:
                raise ValueError(
                    "sim_knobs: the drop_prob knob needs a schedule "
                    "with a nonzero SCALAR drop_prob (the link-fault "
                    "code path must compile in, and the per-edge "
                    "[C, N] form is not scalar-overridable); knob "
                    "value 0.0 then disables drops at run time")
            dpv = float(fault_kv["drop_prob"])
            if not (0.0 <= dpv <= 1.0):
                raise ValueError(
                    f"sim_knobs: drop_prob={dpv} outside [0, 1]")
            kw["faults"] = fp0.replace(drop_prob=jnp.float32(dpv))
        if delay_kv:
            if delays is None:
                raise ValueError(
                    "sim_knobs: the delay_base/delay_jitter knobs "
                    "override compiled DelayParams leaves — pass a "
                    "DelayConfig alongside them (the delay-line code "
                    "path must compile in; its k_slots depth bounds "
                    "the sweepable points)")
            db = int(delay_kv.get("delay_base", delays.base))
            dj = int(delay_kv.get("delay_jitter", delays.jitter))
            delays.validate_point(base=db, jitter=dj)
            kw["delays"] = kw["delays"].replace(
                base=jnp.int32(db), jitter=jnp.int32(dj))

    params = GossipParams(
        subscribed=jnp.asarray(padl(subscribed)),
        cand_sub_bits=jnp.asarray(padl(cand_bits(subscribed))),
        origin_words=jnp.asarray(_pack_bits_pm_np(pad0(origin_bits))),
        deliver_words=jnp.asarray(_pack_bits_pm_np(pad0(deliver_bits))),
        publish_tick=jnp.asarray(msg_publish_tick, dtype=jnp.int32),
        slot_b_words=(jnp.asarray(_pack_bits_pm_np(pad0(slot_b_bits)))
                      if slot_b_bits is not None else None),
        n_true=(n if pad_to_block is not None else None),
        **kw,
    )
    n = n_pad
    w = params.origin_words.shape[0]
    c = cfg.n_candidates
    cdt = (jnp.dtype(score_cfg.counter_dtype) if score_cfg is not None
           else jnp.float32)
    zc = lambda: jnp.zeros((c, n), dtype=cdt)  # noqa: E731
    zt = lambda: jnp.zeros((c, n), dtype=jnp.int16)  # noqa: E731
    zbits = lambda: jnp.zeros((n,), dtype=jnp.uint32)  # noqa: E731
    active0 = None
    if px_candidates is not None:
        if not (cfg.d_hi < px_candidates <= c):
            raise ValueError("need Dhi < px_candidates <= C")
        # each peer starts knowing a random px_candidates-subset of its
        # pool (what discovery handed it before any PX)
        rng0 = np.random.default_rng(seed ^ 0x5F3759DF)
        act = np.zeros(n_pad, dtype=np.uint32)
        for p_chunk in range(0, n, 1 << 16):
            hi = min(n, p_chunk + (1 << 16))
            rows = np.argsort(
                rng0.random((hi - p_chunk, c)), axis=1)[:, :px_candidates]
            bits = np.zeros((hi - p_chunk,), dtype=np.uint32)
            for k in range(px_candidates):
                bits |= np.uint32(1) << rows[:, k].astype(np.uint32)
            act[p_chunk:hi] = bits
        if direct_packed is not None:
            # direct peers are operator-pinned addresses: always held
            # (the reference's direct connect loop re-dials them
            # unconditionally, gossipsub.go:1594-1616) — PX rotation
            # never evicts them (see the rotation site)
            act[:len(direct_packed)] |= direct_packed
        active0 = jnp.asarray(act)

    # round-13 delay lines (models/delays.py): the K-slot circular
    # buffers start empty.  ctrl rows: GRAFT, PRUNE, retraction, plus
    # the broken-promise advert row iff some withholding behavior can
    # be live (the step derives the same predicate at trace time, so
    # the shapes agree).
    pay_line0 = ctrl_line0 = gsp_line0 = adv_line0 = None
    probe_line0 = None
    if delays is not None:
        kd = int(delays.k_slots)
        has_cheat = (score_cfg is not None
                     and (score_cfg.sybil_ihave_spam
                          or promise_break is not None))
        pay_line0 = jnp.zeros((kd, c, w, n), dtype=jnp.uint32)
        ctrl_line0 = jnp.zeros((kd, 3 + int(has_cheat), n),
                               dtype=jnp.uint32)
        if delays_split or delays_counters:
            # delays_counters also needs the gossip-class observer
            # line on the COMBINED path: iwant_served counts the
            # gossip-class arrivals the fused pay_line merged away
            gsp_line0 = jnp.zeros((kd, c, w, n), dtype=jnp.uint32)
        if delays_counters:
            adv_line0 = jnp.zeros((kd, c, w, n), dtype=jnp.uint32)
        if delays_probe:
            # round-20 probe lift: one packed [N] row per send class
            # (eager-forward, IHAVE advert, publish-flood)
            probe_line0 = jnp.zeros((kd, 3, n), dtype=jnp.uint32)
    elif delays_split:
        raise ValueError("delays_split=True needs a DelayConfig")
    elif delays_counters:
        raise ValueError("delays_counters=True needs a DelayConfig")
    elif delays_probe:
        raise ValueError("delays_probe=True needs a DelayConfig")

    state = GossipState(
        mesh=zbits(),
        fanout=zbits(),
        last_pub=jnp.full((n,), -(10 ** 9), dtype=jnp.int32),
        # backoff is REMAINING ticks (int16, decremented each tick;
        # 0 = free) rather than an absolute expiry tick: same blocking
        # semantics, half the per-tick HBM traffic of an i32 [C, N]
        # array, and the gate row becomes tick-independent (> 0)
        backoff=jnp.zeros((c, n), dtype=jnp.int16),
        have=jnp.zeros((w, n), dtype=jnp.uint32),
        recent=jnp.zeros((cfg.history_gossip, w, n), dtype=jnp.uint32),
        first_tick=(jnp.full((w, WORD_BITS, n), -1, dtype=jnp.int16)
                    if track_first_tick else None),
        # behaviour_penalty storage: counter_dtype when the config's
        # decay bounds its magnitude safely below bf16's +1-absorption
        # point, else f32 (ScoreSimConfig.bp_dtype)
        scores=(ScoreState(time_in_mesh=zt(), first_deliveries=zc(),
                           mesh_deliveries=(zc() if score_cfg.track_p3
                                            else None),
                           mesh_failure_penalty=(zc()
                                                 if score_cfg.track_p3
                                                 else None),
                           invalid_deliveries=zc(),
                           behaviour_penalty=jnp.zeros(
                               (c, n),
                               dtype=jnp.dtype(score_cfg.bp_dtype)),
                           time_in_mesh_b=(zt() if cfg.paired_topics
                                           else None))
                if score_cfg is not None else None),
        key=jax.random.PRNGKey(seed),
        tick=jnp.zeros((), dtype=jnp.int32),
        # defense state exists on the no-attack path too (the cutoff is
        # unconditional in the reference, mcache.go:66-80)
        iwant_serves=(zt() if score_cfg is not None else None),
        mesh_b=(zbits() if cfg.paired_topics else None),
        backoff_b=(jnp.zeros((c, n), dtype=jnp.int16)
                   if cfg.paired_topics else None),
        active=active0,
        pay_line=pay_line0, ctrl_line=ctrl_line0, gsp_line=gsp_line0,
        adv_line=adv_line0, probe_line=probe_line0,
    )
    # seed the gate pipeline: tick 0's gate words, exactly what the
    # step's epilogue would have emitted at the end of tick -1
    state = state.replace(
        gates=compute_gates(cfg, score_cfg, params, state,
                            jax.random.key_data(state.key)[-1]),
        gates_fp=gates_fingerprint(cfg, score_cfg))
    return params, state


# --------------------------------------------------------------------------
# Edge transfer: per-edge data -> the partner's view of the same edge
# --------------------------------------------------------------------------


def transfer_bits(bits: jnp.ndarray, cfg: GossipSimConfig,
                  pair: bool = False,
                  n_true: int | None = None) -> jnp.ndarray:
    """Packed-mask edge transfer: what each peer's partners sent it.

    bits: uint32 [N], bit c describing edge (p, p+o_c).  Bit c rolled by
    o_c lands in the partner's bit cinv[c]: out = OR_c roll(bit_c) <<
    cinv[c].  C 1D rolls + shifts, no stacking.

    With ``pair=True`` (requires C <= 16) the word carries TWO C-bit
    masks — low 16 and high 16 bits — and both transfer in the same C
    rolls: the rolls dominate the cost, so two masks for the price of
    one (used for GRAFT+PRUNE handshakes and the packed payload/gossip
    score gates).

    ``n_true`` (round 13, the delayed-exchange path on PADDED kernel
    states): wrap the rolls at the TRUE ring instead of the padded
    length — pad lanes carry zeros.  None (or == len) is the plain
    roll, bit-identically.
    """
    sel = jnp.uint32(0x1_0001) if pair else jnp.uint32(1)
    out = jnp.zeros_like(bits)
    wrap = n_true is not None and n_true != bits.shape[0]
    for c, off in enumerate(cfg.offsets):
        b = (bits >> jnp.uint32(c)) & sel
        rolled = (jnp.concatenate([jnp.roll(b[:n_true], off, axis=0),
                                   b[n_true:]])
                  if wrap else jnp.roll(b, off, axis=0))
        out = out | (rolled << jnp.uint32(cfg.cinv[c]))
    return out


def transfer_mask(mask: jnp.ndarray, cfg: GossipSimConfig) -> jnp.ndarray:
    """edge_transfer for an UNPACKED bool [C, N] mask (tests/analysis;
    the hot path uses transfer_bits)."""
    rows = [None] * cfg.n_candidates
    for c, off in enumerate(cfg.offsets):
        rows[cfg.cinv[c]] = jnp.roll(mask[c], off, axis=0)
    return jnp.stack(rows, axis=0)


def mesh_matrix(state: GossipState, cfg: GossipSimConfig) -> jnp.ndarray:
    """The mesh bitmask as bool [C, N] (tests/analysis)."""
    return expand_bits(state.mesh, cfg.n_candidates)


# --------------------------------------------------------------------------
# The step
# --------------------------------------------------------------------------


def active_score_knobs(params: GossipParams) -> ScoreKnobs | None:
    """The ScoreKnobs override in effect for this sim, whichever
    surface armed it: the legacy ``score_knobs`` param or the round-12
    ``SimKnobs.score`` sub-tree (make_gossip_sim enforces at most one
    of the two)."""
    if params.score_knobs is not None:
        return params.score_knobs
    if params.sim_knobs is not None:
        return params.sim_knobs.score
    return None


def compute_scores(sc: ScoreSimConfig, params: GossipParams,
                   st: GossipState) -> jnp.ndarray:
    """The peer-score formula, densified: f32 [C, N] — peer p's opinion of
    candidate p+o_c (score.go:256-333).  One topic per peer, so the
    per-topic sum collapses to the single topic's contribution.

    Hot-path form: the static P5+P6 term comes precomputed from
    make_gossip_sim (``cand_static_score``) so the tick reads one f32
    array instead of two plus a square.  score_snapshot (the inspection
    path) derives the same sum from components;
    test_score_snapshot_matches_total_and_components pins the two
    together."""
    if params.static_score_zero:
        static = None   # identically-zero bake: skip the [C, N] read
        #   (correct under ANY weights — w * 0 == 0)
    elif (params.cand_static_score is None
          or params.static_score_weights
          != (sc.app_specific_weight, sc.ip_colocation_factor_weight)):
        return score_snapshot(sc, params, st)["score"]
    else:
        static = params.cand_static_score
    s = st.scores
    f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
    tim = f32(s.time_in_mesh)
    invd = f32(s.invalid_deliveries)
    w = sc.topic_weight
    # tournament defense knobs (ScoreKnobs): traced overrides of the
    # baked weights — absent (the default) this is the exact pre-knob
    # arithmetic with python-float constants
    kn = active_score_knobs(params)
    w_inv = (kn.invalid_message_deliveries_weight if kn is not None
             else sc.invalid_message_deliveries_weight)
    w_bp = (kn.behaviour_penalty_weight if kn is not None
            else sc.behaviour_penalty_weight)
    # summed per-topic contribution (P1..P4).  With equal topic weights
    # the LINEAR terms' per-topic sums collapse into the aggregate
    # counters exactly (P1 stays per-slot because the meshes differ).
    # Known deviation in paired mode: P4's square and the P2 cap apply
    # to the aggregate across the pair rather than per topic — exact
    # when the traffic concentrates in one of the two topics, and up to
    # 2x the P4 penalty (conservative, anti-attacker) when an invalid
    # spammer splits evenly; test_multi_topic_score_sum_matches_core
    # pins the exact regime against core/score.py.
    topic_part = (w * sc.time_in_mesh_weight
                  * jnp.minimum(tim / sc.time_in_mesh_quantum,
                                sc.time_in_mesh_cap)
                  + (w * sc.first_message_deliveries_weight)
                  * f32(s.first_deliveries)
                  + (w * w_inv)
                  * invd * invd)
    if s.time_in_mesh_b is not None:
        tim_b = f32(s.time_in_mesh_b)
        topic_part = topic_part + (w * sc.time_in_mesh_weight
                                   * jnp.minimum(
                                       tim_b / sc.time_in_mesh_quantum,
                                       sc.time_in_mesh_cap))
    if sc.track_p3:
        c = s.time_in_mesh.shape[0]
        in_mesh = expand_bits(st.mesh, c)
        deficit = jnp.maximum(
            0.0, sc.mesh_message_deliveries_threshold
            - f32(s.mesh_deliveries))
        active = tim > sc.mesh_message_deliveries_activation
        topic_part = (topic_part
                      + (w * sc.mesh_message_deliveries_weight)
                      * jnp.where(in_mesh & active, deficit * deficit,
                                  0.0)
                      + (w * sc.mesh_failure_penalty_weight)
                      * f32(s.mesh_failure_penalty))
    if sc.topic_score_cap > 0:
        # the cap applies to the summed topic contribution only,
        # before P5..P7 (score.go:256-268)
        topic_part = jnp.minimum(topic_part, sc.topic_score_cap)
    bp_excess = jnp.maximum(
        0.0, f32(s.behaviour_penalty) - sc.behaviour_penalty_threshold)
    if static is not None:
        topic_part = topic_part + static
    return topic_part + w_bp * bp_excess * bp_excess


def score_snapshot(sc: ScoreSimConfig, params: GossipParams,
                   st: GossipState) -> dict:
    """Per-component score breakdown for every edge — the simulator's
    WithPeerScoreInspect (score.go:147-175, PeerScoreSnapshot: inspect
    per-peer totals plus per-topic P1..P4 and top-level P5..P7).

    Returns a dict of f32 [C, N] arrays: weighted contributions
    p1..p7 (p3/p3b zero when P3 tracking is off) and their sum 'score'
    (== compute_scores).  Row c, column p = peer p's view of candidate
    p+o_c.
    """
    s = st.scores
    c = s.time_in_mesh.shape[0]
    f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
    tim = f32(s.time_in_mesh)
    invd = f32(s.invalid_deliveries)
    w = sc.topic_weight
    kn = active_score_knobs(params)
    w_inv = (kn.invalid_message_deliveries_weight if kn is not None
             else sc.invalid_message_deliveries_weight)
    w_bp = (kn.behaviour_penalty_weight if kn is not None
            else sc.behaviour_penalty_weight)
    out = {
        "p1_time_in_mesh": w * sc.time_in_mesh_weight * jnp.minimum(
            tim / sc.time_in_mesh_quantum, sc.time_in_mesh_cap),
        "p2_first_deliveries": (w * sc.first_message_deliveries_weight
                                * f32(s.first_deliveries)),
        "p4_invalid_deliveries": (w * w_inv
                                  * invd * invd),
        "p5_app_specific": (sc.app_specific_weight
                            * params.cand_app_score),
        "p6_ip_colocation": (sc.ip_colocation_factor_weight
                             * params.cand_colo_excess
                             * params.cand_colo_excess),
    }
    if sc.track_p3:
        in_mesh = expand_bits(st.mesh, c)
        deficit = jnp.maximum(
            0.0, sc.mesh_message_deliveries_threshold
            - f32(s.mesh_deliveries))
        active = tim > sc.mesh_message_deliveries_activation
        out["p3_mesh_delivery_deficit"] = (
            w * sc.mesh_message_deliveries_weight
            * jnp.where(in_mesh & active, deficit * deficit, 0.0))
        out["p3b_mesh_failure_penalty"] = (
            w * sc.mesh_failure_penalty_weight
            * f32(s.mesh_failure_penalty))
    else:
        zero = jnp.zeros_like(tim)
        out["p3_mesh_delivery_deficit"] = zero
        out["p3b_mesh_failure_penalty"] = zero
    if s.time_in_mesh_b is not None:
        out["p1b_time_in_mesh"] = (
            w * sc.time_in_mesh_weight * jnp.minimum(
                f32(s.time_in_mesh_b) / sc.time_in_mesh_quantum,
                sc.time_in_mesh_cap))
    bp_excess = jnp.maximum(
        0.0, f32(s.behaviour_penalty) - sc.behaviour_penalty_threshold)
    out["p7_behaviour_penalty"] = (w_bp
                                   * bp_excess * bp_excess)
    topic_part = (out["p1_time_in_mesh"] + out["p2_first_deliveries"]
                  + out["p3_mesh_delivery_deficit"]
                  + out["p3b_mesh_failure_penalty"]
                  + out["p4_invalid_deliveries"]
                  + out.get("p1b_time_in_mesh", 0.0))
    if sc.topic_score_cap > 0:
        # cap binds the summed topic contribution only (score.go:256-268)
        topic_part = jnp.minimum(topic_part, sc.topic_score_cap)
    out["score"] = (topic_part + out["p5_app_specific"]
                    + out["p6_ip_colocation"]
                    + out["p7_behaviour_penalty"])
    return out


def gates_fingerprint(cfg: GossipSimConfig,
                      sc: ScoreSimConfig | None) -> int:
    """Stable fingerprint of the scalar config fields the carried gate
    words depend on (thresholds, decays, weights, sampling mode, ...).
    Stored as ``GossipState.gates_fp`` when gates are emitted; the step
    refuses a state whose fingerprint differs from its own config's."""
    import zlib
    from dataclasses import fields as _dc_fields

    def scalars(obj):
        return tuple(
            (f.name, getattr(obj, f.name)) for f in _dc_fields(obj)
            if isinstance(getattr(obj, f.name),
                          (bool, int, float, str, type(None))))

    # offsets are a tuple (not caught by the scalar filter) but define
    # the ring topology the backoff/target rows were computed over —
    # same-shape different-seed rings must fingerprint differently
    desc = (("C", cfg.n_candidates),
            ("offsets", tuple(int(o) for o in cfg.offsets)),
            scalars(cfg), None if sc is None else scalars(sc))
    return zlib.crc32(repr(desc).encode())


def compute_gates(cfg: GossipSimConfig, sc: ScoreSimConfig | None,
                  params: GossipParams, st: GossipState,
                  salt: jnp.ndarray) -> tuple:
    """Packed per-tick gate words (tuple of G uint32 [N]) for ``st.tick``.

    The tick prologue's entire read of the [C, N] numeric state, packed
    into G uint32 words per peer.  Scored rows (in order):

      0 accept   — score >= graylist threshold (AcceptFrom,
                   gossipsub.go:584)
      1 gossip   — score >= gossip threshold (handleIHave/emitGossip,
                   gossipsub.go:610,1681)
      2 publish  — score >= publish threshold (gossipsub.go:956)
      3 nonneg   — score >= 0 (mesh retention/graft, gossipsub.go:1340)
      4 payload  — accept ∧ RED-gater draw (peer_gater.go:320-363)
      5 targets  — this tick's lazy-gossip IHAVE targets (emitGossip,
                   gossipsub.go:1656-1712; the only always-on selection)
      6 backoff  — remaining backoff > 0 (no re-GRAFT, gossipsub.go:747)
      7 backoff_b (paired mode only)

    Unscored sims carry (targets, backoff(, backoff_b)).

    The step normally does NOT call this at tick start: the previous
    tick's epilogue (or the pallas receive kernel) emits the same rows
    while the updated counters are still in registers/VMEM, and the
    result rides the state (``GossipState.gates``).  Emission applies
    the same storage rounding (bf16 counters) a tick-start recompute
    would read back, so the two formulations are bit-identical;
    tests/test_gossipsub_sim.py::test_pipelined_gates_match_recompute
    pins them against each other.
    """
    C = cfg.n_candidates
    n = st.mesh.shape[0]
    n_stream = params.n_true if params.n_true is not None else n
    tick = st.tick
    ALL = jnp.uint32((1 << C) - 1)
    Z = jnp.uint32(0)
    rows = []
    if sc is not None:
        score = compute_scores(sc, params, st)              # [C, N]
        kn = active_score_knobs(params)
        gray_thr = (kn.graylist_threshold if kn is not None
                    else sc.graylist_threshold)
        gsp_thr = (kn.gossip_threshold if kn is not None
                   else sc.gossip_threshold)
        accept_bits = pack_rows(score >= gray_thr)
        rows = [accept_bits,
                pack_rows(score >= gsp_thr),
                pack_rows(score >= sc.publish_threshold),
                pack_rows(score >= 0)]
        # RED gater: under invalid-traffic pressure, payload from an
        # edge is accepted with its goodput probability
        # (peer_gater.go:320-363).  Stats are keyed by SOURCE IP
        # (peer_gater.go:119-151): when candidates share an address
        # (cand_same_ip, built only if some IP is shared) each edge's
        # goodput uses the sums over its same-IP siblings, so sybils
        # behind one address share fate at the gater as in the
        # reference — not just through the P6 score term.
        s0 = st.scores
        f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
        invd = f32(s0.invalid_deliveries)
        fdel = f32(s0.first_deliveries)
        inv_tot = invd.sum(axis=0)                          # [N]
        del_tot = fdel.sum(axis=0)
        pressure = 16.0 * inv_tot / (1.0 + del_tot + 16.0 * inv_tot)
        gater_on = pressure > 0.33
        def gater_draw():
            # the same-IP sibling aggregation lives INSIDE the cond:
            # built outside, it would be a cond operand and run on
            # every clean tick too
            if params.cand_same_ip is not None:
                inv_g = jnp.zeros_like(invd)
                fd_g = jnp.zeros_like(fdel)
                for cc in range(C):
                    sib = expand_bits(params.cand_same_ip[cc], C)
                    inv_g = inv_g + jnp.where(sib, invd[cc][None, :],
                                              0.0)
                    fd_g = fd_g + jnp.where(sib, fdel[cc][None, :],
                                            0.0)
            else:
                inv_g, fd_g = invd, fdel
            goodput = (1.0 + fd_g) / (1.0 + fd_g + 16.0 * inv_g)
            u_gater = lane_uniform((C, n), tick, 6, salt,
                                   stride=n_stream)
            return pack_rows(u_gater < goodput) | jnp.where(
                gater_on, Z, ALL)

        # the RED draw only matters while some peer is under pressure
        # (invalid traffic present); clean runs skip the [C, N] hash +
        # compare + pack entirely
        gater_bits = jax.lax.cond(
            jnp.any(gater_on), gater_draw,
            lambda: jnp.full_like(accept_bits, ALL))
        rows.append(accept_bits & gater_bits)               # payload

    # lazy-gossip targets: random non-mesh subscribed candidates,
    # max(Dlazy, factor * |elig|), both sides above the gossip
    # threshold (emitGossip gossipsub.go:1656-1712).  st.fanout is
    # pre-tick state (fanout-only peers are unsubscribed, already
    # zeroed by the sub gate — the ~fanout term is belt-and-braces).
    rows.append(gossip_targets_row(
        cfg, sc, params, mesh=st.mesh, fanout=st.fanout,
        mesh_b=st.mesh_b, active=st.active,
        gossip_row=(rows[1] if sc is not None else None),
        tick=tick, salt=salt, n_stream=n_stream, n=n))

    rows.append(pack_rows(st.backoff > 0))
    if cfg.paired_topics:
        rows.append(pack_rows(st.backoff_b > 0))
    # a TUPLE of [N] words — stacking into [G, N] would make every row
    # read a sublane-sliced tile read (see GossipState.gates)
    return tuple(rows)


def gossip_targets_row(cfg: GossipSimConfig, sc: ScoreSimConfig | None,
                       params: GossipParams, *, mesh, fanout, mesh_b,
                       active, gossip_row, tick, salt, n_stream, n):
    """The lazy-gossip targets gate row (compute_gates row 5 scored /
    0 unscored): random non-mesh subscribed candidates, max(Dlazy,
    factor * |elig|), both sides above the gossip threshold
    (emitGossip gossipsub.go:1656-1712; the only always-on selection).

    Shared by compute_gates and the kernel path's PX re-emission (the
    kernel can't know the POST-rotation active set, so PX configs
    recompute this row from the rotated state — see _finish_kernel)."""
    C = cfg.n_candidates
    ALL = jnp.uint32((1 << C) - 1)
    Z = jnp.uint32(0)
    sub_all = jnp.where(params.subscribed, ALL, Z)
    elig = params.cand_sub_bits & ~mesh & ~fanout & sub_all
    if active is not None:
        elig = elig & active
    if cfg.paired_topics:
        # shared gossip stream across the two topic slots (one Dlazy
        # selection covers both; documented deviation from per-topic
        # emission): exclude slot-B mesh members too
        elig = elig & ~mesh_b
    if params.flood_proto is not None:
        # no IHAVE to floodsub-protocol peers (no control protocol)
        elig = elig & ~params.cand_flood_bits
    if gossip_row is not None:
        elig = elig & gossip_row                            # gossip gate
    n_elig = popcount32(elig)
    # round-12 knobs: d_lazy / gossip_factor ride the params as traced
    # scalars when armed — value-identical arithmetic at the defaults
    skn = params.sim_knobs
    k_lazy = (skn.d_lazy if skn is not None
              else jnp.int32(cfg.d_lazy))
    k_factor = (skn.gossip_factor if skn is not None
                else cfg.gossip_factor)
    n_gossip = jnp.maximum(
        k_lazy,
        (k_factor * n_elig.astype(jnp.float32)).astype(
            jnp.int32))
    if cfg.binomial_gossip_sampling:
        # Bernoulli(k/|elig|) per eligible edge: same inclusion
        # probability as the exact k-subset, no [C, C, N] rank
        # (see GossipSimConfig.binomial_gossip_sampling)
        p_g = jnp.minimum(
            1.0, n_gossip.astype(jnp.float32)
            / jnp.maximum(n_elig, 1).astype(jnp.float32))
        u_g = lane_uniform((C, n), tick, 1, salt, stride=n_stream)
        targets = elig & pack_rows(u_g < p_g[None, :])
    else:
        targets = select_k_bits(elig, n_gossip,
                                (C, tick, 1, salt, n_stream))
    if params.flood_proto is not None:
        targets = jnp.where(params.flood_proto, Z, targets)
    if sc is not None and sc.sybil_ihave_spam:
        # IHAVE-spamming sybils advertise to every subscribed
        # candidate ids they never deliver (gossipsub_spam_test.go:135)
        targets = jnp.where(params.sybil, params.cand_sub_bits,
                            targets)
    return targets


def px_rotate(cfg: GossipSimConfig, params: GossipParams, *,
              active, rot, keep, sel_k, tick, salt, n_stream):
    """PX-driven candidate refresh (gossipsub.go:856-937), shared by
    the XLA step's phase 4b and the kernel path's epilogue so the two
    can never drift: received PRUNEs/PRUNE-responses (plus own
    negative-score drops, folded into ``rot`` by the caller) rotate
    the pruned address out of the active set and dial a fresh pool
    candidate in; edges in ``keep`` (meshes, fanout, pinned direct
    peers) are never deactivated."""
    C = cfg.n_candidates
    ALL = jnp.uint32((1 << C) - 1)
    if params.cand_direct is not None:
        # operator-pinned direct addresses are re-dialed
        # unconditionally (gossipsub.go:1594-1616): PX churn never
        # evicts them from the active set
        keep = keep | params.cand_direct
    deact = rot & active & ~keep
    n_rot = popcount32(deact)
    # exclude edges already folding in via keep, or a rotation slot
    # would be wasted re-selecting one of them
    pool_new = ~active & ~keep & params.cand_sub_bits & ALL
    repl = jax.lax.cond(
        jnp.any(n_rot > 0),
        lambda: sel_k(pool_new, n_rot, (C, tick, 7, salt, n_stream)),
        lambda: jnp.zeros_like(active))
    # live connections are held addresses: an ACCEPTED inbound GRAFT
    # teaches the grafter's address even if it wasn't in the active
    # set (the dialer always knows the dialee), so mesh/fanout edges
    # fold in and mesh ⊆ active is invariant
    return (active & ~deact) | repl | keep


def refresh_gates(cfg: GossipSimConfig, sc: ScoreSimConfig | None,
                  params: GossipParams, st: GossipState) -> GossipState:
    """Recompute the carried gate words after manual state surgery.

    The pipelined gates are a pure function of the state fields they
    read — counters, backoff(_b), mesh(_b), fanout, active — plus the
    static params; any test/tool that edits ANY of those via
    ``state.replace`` must refresh them or the next tick acts on stale
    gates."""
    if st.gates is None:
        return st
    return st.replace(
        gates=compute_gates(cfg, sc, params, st,
                            jax.random.key_data(st.key)[-1]),
        gates_fp=gates_fingerprint(cfg, sc))


def kernel_capability(cfg: GossipSimConfig, sc: ScoreSimConfig | None,
                      params: GossipParams,
                      state: GossipState) -> str | None:
    """Capability dispatch for the pallas receive path: ``None`` when
    the mosaic kernel supports this configuration, else the refusal
    message the step raises (message-matched by tests — keep stable).

    Fault schedules and telemetry configs are CAPABILITIES, not
    refusals: the kernel threads the per-tick alive/link mask words
    through its VMEM pass and accumulates the TelemetryFrame counter
    tallies as in-kernel reductions (ops/pallas/receive.py).  What
    remains refused is genuinely unsupported: C > 16 (the u16
    pair-packing and ctrl-byte layout), W == 0 (no payload stream to
    schedule), mixed-protocol overlays (flood_proto), P3 bookkeeping
    (needs the split-loop provenance the fused kernel elides), a
    state without carried gates, a re-weighted NONZERO static
    score bake (the kernel adds the baked P5+P6 term as-is; an
    all-zero bake is weight-independent), and Byzantine payload
    mutation (per-edge content corruption needs the per-edge receive
    loops the fused kernel elides).

    Traced knobs are a CAPABILITY since round 12: the ScoreKnobs
    defense sub-tree and the cheap SimKnobs scalars the kernel
    consumes in-VMEM (gossip_factor, d_lazy, backoff_ticks) ride one
    SMEM f32 operand; the degree-family knobs are consumed in the
    shared XLA prologue and need no kernel work.  The ONE knob that
    legitimately stays XLA-only is ``gossip_retransmission`` under
    the IWANT-spam attack config — its serve-budget multiply runs
    in-kernel from the baked constant, so a SimKnobs point on an
    iwant-spam config is refused by name (graftlint carries the
    matching probe).

    Since round 20 this is a thin call onto the capability planner
    (models/plan.py) — every refusal string is defined THERE, once."""
    verdict = _plan.plan_kernel_step(cfg, sc, params, state)
    return (None if isinstance(verdict, _plan.ExecutionPlan)
            else verdict.message)


#: VMEM the fused window's resident carry may claim — defined by the
#: capability planner (models/plan.py), re-exported for the existing
#: call sites.
FUSED_VMEM_BUDGET = _plan.FUSED_VMEM_BUDGET


def kernel_ticks_fused_capability(
        cfg: GossipSimConfig, sc: ScoreSimConfig | None,
        params: GossipParams, state: GossipState, ticks: int, *,
        vmem_budget_bytes: int = FUSED_VMEM_BUDGET,
        sharded: bool = False, devices: int = 1) -> str | None:
    """Capability dispatch for the round-16 tick-resident window:
    ``None`` when T ticks can fold into one resident pallas_call, else
    the named refusal ``make_fused_window`` falls back (or raises) by.
    Every refusal is prefixed ``kernel_ticks_fused:`` and
    message-matched by graftlint contract probes — keep stable.

    Residency is refused where it is genuinely impossible, and the
    byte-bound refusals REPORT the bytes: the resident carry must fit
    the VMEM budget twice over (entry pair + revisited output pair),
    so scored accumulators, delay lines, and large C·W carries fall
    back to the per-tick kernel with the working set in the message.

    With ``sharded=True`` (round 17) the window composes with the
    multi-chip dispatch: the PER-SHARD carry plus the double-buffered
    halo slots must fit, the shard extent must hold whole lane tiles,
    and the candidate reach must stay inside the ``devices``-shard
    ring — each refused by name; delay-armed sims keep the existing
    per-tick refusal (the K-slot dequeue runs between kernel ticks).

    Since round 20 this is a thin call onto the capability planner
    (models/plan.py) — every refusal string is defined THERE, once."""
    verdict = _plan.plan_fused_window(
        cfg, sc, params, state, ticks,
        vmem_budget_bytes=vmem_budget_bytes, sharded=sharded,
        devices=devices)
    return (None if isinstance(verdict, _plan.ExecutionPlan)
            else verdict.message)


def make_gossip_step(cfg: GossipSimConfig,
                     score_cfg: ScoreSimConfig | None = None,
                     use_pallas_select: bool | None = None,
                     use_pallas_receive: bool | None = None,
                     receive_block: int = 8192,
                     receive_interpret: bool = False,
                     force_split: bool = False,
                     pipeline_gates: bool = True,
                     shard_mesh=None,
                     shard_axis: str = "peers",
                     telemetry: _telemetry.TelemetryConfig | None = None,
                     rpc_probe: bool = False,
                     invariants: _invariants.InvariantConfig | None
                     = None):
    """Build the jittable (params, state) -> (state, delivered_words) core.

    With ``rpc_probe=True`` (round 10) the step additionally returns a
    per-tick dict of per-edge RPC words as its LAST element — the
    ATTEMPT masks (eager-forward / IHAVE / GRAFT / PRUNE edges before
    fault masking), the content words they would carry, and the
    per-tick fault masks — which
    ``gossip_run_rpc_snapshots`` collects and
    ``interop.export.rpc_events`` reconstructs into the reference's
    per-RPC SEND_RPC / RECV_RPC / DROP_RPC metadata streams.  Probe
    data is a pure READOUT (the state trajectory is bit-identical) and
    works on both execution paths; paired-topic overlays are
    probe-supported since round 13 (per-slot masks + slot-split
    payload in the snapshot); delay-armed sims are probe-supported
    since round 20 (build with ``delays_probe=True`` — the snapshot
    gains ``arr_*`` arrival masks dequeued from a K-slot probe line);
    mixed-protocol overlays are not (they raise by name).

    With ``telemetry`` (models/telemetry.py) the step instead returns
    ``(state, delivered_words, TelemetryFrame)`` — per-tick protocol
    counters computed in-scan; run it through the telemetry runners
    (telemetry_run / telemetry_run_curve / telemetry_run_batch).  The
    state trajectory is bit-identical to the telemetry-free step
    (telemetry only READS), and ``telemetry=None`` (the default)
    compiles the exact pre-telemetry step.  Both execution paths
    support it: on the pallas kernel the RPC/duplicate counters
    accumulate as in-kernel reductions over views already in VMEM
    (frames match the XLA path bit-for-bit; the scores group costs
    one extra [C, N] pass on the kernel path — see kernel_capability).

    Per tick:
      1. inject due publishes (Topic.Publish -> rt.Publish, topic.go:207)
      2. eager forward: newly-acquired words flow one hop along mesh ∪
         fanout edges (forwardMessage to mesh, gossipsub.go:989-999)
      3. lazy gossip: IHAVE of the recent window to Dlazy/gossip-factor
         random non-mesh candidates; receivers pull what they lack
         (emitGossip gossipsub.go:1656-1712 + handleIHave/IWant :610-711)
      4. heartbeat maintenance: graft to D when deg<Dlo, prune to D when
         deg>Dhi, GRAFT/PRUNE handshake with backoff, fanout TTL
         (heartbeat gossipsub.go:1299-1552)

    With ``invariants`` (models/invariants.py, round 11) the step
    additionally evaluates the ACL2s-style safety properties as cheap
    boolean reductions over values the tick already computed — a pure
    READOUT folded into the state's ``inv_viol``/``inv_first`` carry
    (arm the state with invariants.attach first).  The trajectory of
    every other state field is bit-identical with the checker on, and
    ``invariants=None`` (the default) compiles the exact pre-invariant
    step (both pinned by tests/test_invariants.py).  Works on BOTH
    execution paths: the kernel epilogue hands the checker the same
    outputs the XLA epilogue does.

    With score_cfg, the v1.1 hardening layer is woven through every phase:
    start-of-tick scores gate inbound RPCs (graylist), gossip exchange
    (gossip threshold), and publish flooding (publish threshold); delivery
    provenance per candidate bit feeds the P2/P3/P4 counters; mesh
    maintenance prunes negative-score peers, keeps the Dscore best + Dout
    outbound on oversubscription (gossipsub.go:1376-1435), and
    opportunistically grafts when the mesh median sags
    (gossipsub.go:1467-1498); a RED gater drops payload from edges with
    bad goodput under invalid-traffic pressure (peer_gater.go:320-363).
    """
    C = cfg.n_candidates
    sc = score_cfg
    paired = cfg.paired_topics
    tel = telemetry
    icfg = invariants
    # wire-framing constants measured from the pb/rpc.py encodings at
    # build time (host side), baked into the step as scalars
    ws = _telemetry.wire_sizes(tel) if tel is not None else None
    step_gates_fp = gates_fingerprint(cfg, sc)
    offsets = tuple(int(o) for o in cfg.offsets)
    cinv = cfg.cinv
    OUT_MASK = jnp.uint32(cfg.outbound_mask)
    ALL = jnp.uint32((1 << C) - 1)
    Z = jnp.uint32(0)
    pc = jax.lax.population_count
    if paired and (C > 16 or force_split
                   or (sc is not None and sc.track_p3)):
        raise ValueError("paired_topics needs the combined path "
                        "(C<=16, no track_p3/force_split)")
    # rpc_probe coverage (round 13): PAIRED-TOPIC overlays are
    # probe-supported — the snapshot carries the per-slot masks
    # (fwd_b / graft_b / prune_b) and the slot-split payload words
    # (fresh_a / fresh_b), and interop.export.rpc_events reconstructs
    # per-slot GRAFT/PRUNE topics and a slot-split IHAVE.  The ONE
    # remaining probe refusal is MIXED-PROTOCOL overlays (flood_proto,
    # raised at trace time in the step where the params are visible);
    # delay-armed sims are probe-supported since round 20 (the
    # snapshot's arrival leaves dequeue from the K-slot probe line —
    # build with delays_probe=True).

    # random-k selection backend.  The mosaic kernel (bit-identical
    # output) is kept as an option, but measured inside the real scanned
    # step (tools/profile_ablate.py, state loop-carried) XLA's fusion
    # already makes selection nearly free (ablating select_k_bits moves
    # the step < 0.1 ms), and the kernel is marginally slower end to end
    # — so it stays off by default.  It also has no GSPMD partitioning
    # rule; sharded runs must keep the XLA form.
    if use_pallas_select is None:
        use_pallas_select = False
    if use_pallas_select:
        from ..ops.pallas.select import select_k_bits_pallas

        def sel_k(elig, k, spec):
            c, tick, phase, salt = spec[:4]
            stride = spec[4] if len(spec) > 4 else elig.shape[0]
            return select_k_bits_pallas(
                elig, k, lane_seed(tick, phase, salt), c,
                stride=stride)
    else:
        sel_k = select_k_bits

    def apply_invariants(params, old_state, new_state, have_pre,
                         rejoin_w, delivered_now, f_alive_w):
        """Fold one tick's invariant checks (models/invariants.py)
        into the state carry — a pure readout of the step's outputs,
        shared verbatim by the XLA and kernel epilogues (which is why
        the checker needs no in-kernel work).  On padded states every
        operand is sliced to the TRUE peers: kernel pad lanes may
        carry wrapped-view garbage (see iwant_serve_level) and must
        not trip a check."""
        n_true = params.n_true

        def tr(a):
            return a if (a is None or n_true is None) \
                else a[..., :n_true]

        sub_all_t = jnp.where(tr(params.subscribed), ALL, Z)
        bits = _invariants.delivery_violations(
            icfg, tr(have_pre), tr(new_state.have), tr(delivered_now),
            alive_w=tr(f_alive_w),
            invalid_words=(params.invalid_words if sc is not None
                           else None),
            allowed_clear_w=tr(rejoin_w))
        honest_all = None
        if sc is not None and (sc.sybil_graft_flood
                               or sc.sybil_eclipse):
            # attackers that bypass their own backoff legitimately
            # hold mesh edges inside it (the partner accepted)
            bypass = jnp.zeros(params.subscribed.shape, dtype=bool)
            if sc.sybil_graft_flood and params.sybil is not None:
                bypass = bypass | params.sybil
            if sc.sybil_eclipse and params.eclipse_sybil is not None:
                bypass = bypass | params.eclipse_sybil
            honest_all = jnp.where(bypass, Z, ALL)
        bits = bits | _invariants.gossip_mesh_violations(
            icfg, C, mesh_new=tr(new_state.mesh),
            backoff_new=tr(new_state.backoff),
            cand_sub_bits=tr(params.cand_sub_bits),
            sub_all=sub_all_t, honest_all=tr(honest_all),
            mesh_b_new=tr(new_state.mesh_b),
            backoff_b_new=tr(new_state.backoff_b))
        if sc is not None and new_state.scores is not None:
            bits = bits | _invariants.gossip_score_violations(
                icfg, sc,
                jax.tree_util.tree_map(tr, new_state.scores),
                mesh_new=tr(new_state.mesh),
                mesh_b_new=tr(new_state.mesh_b))
        viol, first = _invariants.fold(
            old_state.inv_viol, old_state.inv_first, bits,
            old_state.tick)
        return new_state.replace(inv_viol=viol, inv_first=first)

    def _finish_kernel(*, params, state, fanout, last_pub, injected,
                       fresh, adv, targets, withhold, out_bits, grafts,
                       dropped, mesh_sel, a_sent, would_accept,
                       backoff_bits2, sub_all, payload_bits,
                       gossip_bits, accept_bits, valid_w, tick, salt,
                       flood_bits=None, neg=None, sel_b=None,
                       fresh_b=None, fmasks=None, have_pre=None,
                       rejoin_w=None, dex=None):
        """Pallas path: one mega-kernel does the payload receive,
        handshake resolution, and per-edge counter/backoff updates in
        a single HBM pass over the [C, N] state (ops/pallas/receive).

        ``fmasks`` (fault configs): the per-tick mask words — sender
        sides are masked HERE on the [N] ctrl words before byte
        packing (they ride the existing DMA slots), receiver sides go
        in as the kernel's alive-word operand.  With telemetry, the
        in-kernel counter tallies — plus the round-10 latency bucket
        rows when latency_hist is on — come back as one
        [TEL_ROWS + L, 128] reduction output and the frame is
        assembled in the epilogue, bit-identical to the XLA path's."""
        from ..ops.pallas.receive import (
            CTRL_A, CTRL_DROP, CTRL_FLOOD, CTRL_GRAFT,
            CTRL_OUT, CTRL_ADV, CTRL_TGT,
            CTRL2_A_B, CTRL2_DROP_B, CTRL2_GRAFT_B, CTRL2_OUT_B,
            TEL_PAYLOAD, TEL_IHAVE_IDS, TEL_IWANT_SERVED, TEL_RECV,
            TEL_IWANT_REQ, TEL_IHAVE_RPCS, TEL_IWANT_RPCS, TEL_NEW_IDS,
            extend_wrap, make_receive_update, n_gate_rows, plan,
            sharded_receive)

        n_true = params.n_true
        n_pad = params.subscribed.shape[0]
        W = state.have.shape[0]
        pln = plan(n_true, offsets, receive_block)
        if pln["n_pad"] != n_pad:
            raise ValueError(
                f"state padded to {n_pad}, kernel plan wants "
                f"{pln['n_pad']} (pad_to_block == receive_block?)")
        # raw advert (CTRL_ADV) vs delivering advert (CTRL_TGT): their
        # difference at the receiver IS the broken promise — behavioral
        # P7, no oracle flag in the kernel
        tgt_deliver = (targets if withhold is None
                       else jnp.where(withhold, Z, targets))
        track_promises = withhold is not None

        def bit_of(word, c):
            return (word >> jnp.uint32(c)) & jnp.uint32(1)

        g_tx, d_tx, a_tx = grafts, dropped, a_sent
        if fmasks is not None:
            # handshake RPCs are sends like any other: a dead peer (or
            # a down link) transmits no GRAFT/PRUNE/A this tick.  The
            # LOCAL effects of ``dropped`` (mesh removal, own backoff)
            # still apply via the drop_ref operand below — only the
            # notification is lost, exactly the XLA raw_transfers
            # contract.  out_bits/targets arrive pre-masked.
            so = fmasks["send_ok"]
            g_tx, d_tx, a_tx = grafts & so, dropped & so, a_sent & so
        ctrl_rows = []              # u8 [n_pad] per sender edge
        for c in range(C):
            b = ((bit_of(out_bits, c) << jnp.uint32(CTRL_OUT))
                 | (bit_of(tgt_deliver, c) << jnp.uint32(CTRL_TGT))
                 | (bit_of(g_tx, c) << jnp.uint32(CTRL_GRAFT))
                 | (bit_of(d_tx, c) << jnp.uint32(CTRL_DROP))
                 | (bit_of(a_tx, c) << jnp.uint32(CTRL_A))
                 | (bit_of(targets, c) << jnp.uint32(CTRL_ADV)))
            if flood_bits is not None:
                b = b | (bit_of(flood_bits, c)
                         << jnp.uint32(CTRL_FLOOD))
            ctrl_rows.append(b.astype(jnp.uint8))
        ctrl2_rows = None
        if paired:
            # second ctrl byte: the SLOT-B flags of the same edge
            out_b_bits = state.mesh_b
            if params.cand_direct is not None:
                # direct peers are eager-forward targets on every
                # topic (gossipsub.go:945-950)
                out_b_bits = out_b_bits | (params.cand_direct
                                           & params.cand_sub_bits)
            if (sc is not None and sc.sybil_eclipse
                    and params.eclipse_sybil is not None):
                # eclipse attackers are silent on the slot-B mesh too
                out_b_bits = jnp.where(params.eclipse_sybil, Z,
                                       out_b_bits)
            gb_tx, db_tx, ab_tx = (sel_b["grafts"], sel_b["dropped"],
                                   sel_b["a_sent"])
            if fmasks is not None:
                # slot-B forwards and handshake are sends too
                so = fmasks["send_ok"]
                out_b_bits = out_b_bits & so
                gb_tx, db_tx, ab_tx = (gb_tx & so, db_tx & so,
                                       ab_tx & so)
            ctrl2_rows = []
            for c in range(C):
                b2 = ((bit_of(out_b_bits, c)
                       << jnp.uint32(CTRL2_OUT_B))
                      | (bit_of(gb_tx, c)
                         << jnp.uint32(CTRL2_GRAFT_B))
                      | (bit_of(db_tx, c)
                         << jnp.uint32(CTRL2_DROP_B))
                      | (bit_of(ab_tx, c)
                         << jnp.uint32(CTRL2_A_B)))
                ctrl2_rows.append(b2.astype(jnp.uint8))
        seen_st = jnp.stack([state.have[w] | injected[w]
                             for w in range(W)])
        inj_st = jnp.stack(injected)
        # mixed lane seeds for the next tick's emissions: phase-6
        # gater draw, phase-1 gossip-target sampling
        gseeds = jnp.stack([lane_seed(tick + 1, 6, salt),
                            lane_seed(tick + 1, 1, salt)])
        cdt = (jnp.dtype(sc.counter_dtype) if sc is not None else None)
        head = ([jnp.stack(valid_w)] if sc is not None else []) + [gseeds]
        # round-12 knobs: the in-kernel consumers (gossip_factor +
        # d_lazy in the next-tick targets emission, backoff_ticks in
        # the backoff write, the four ScoreKnobs fields in the score /
        # gate stage) ride ONE f32 SMEM vector.  Order is the kernel's
        # KNOB_* layout (ops/pallas/receive.py); i32-valued knobs are
        # exact through the f32 carry (values << 2^24).  The
        # degree-family knobs are consumed in the shared prologue
        # above and need nothing here.
        skn_k = params.sim_knobs
        kkn = active_score_knobs(params)
        with_kn = skn_k is not None or kkn is not None
        if with_kn:
            kvals = [
                (skn_k.gossip_factor if skn_k is not None
                 else cfg.gossip_factor),
                (skn_k.d_lazy if skn_k is not None else cfg.d_lazy),
                (skn_k.backoff_ticks if skn_k is not None
                 else cfg.backoff_ticks),
            ]
            if sc is not None:
                kvals += [
                    (kkn.invalid_message_deliveries_weight
                     if kkn is not None
                     else sc.invalid_message_deliveries_weight),
                    (kkn.behaviour_penalty_weight if kkn is not None
                     else sc.behaviour_penalty_weight),
                    (kkn.graylist_threshold if kkn is not None
                     else sc.graylist_threshold),
                    (kkn.gossip_threshold if kkn is not None
                     else sc.gossip_threshold),
                ]
            head = head + [jnp.stack(
                [jnp.asarray(v, dtype=jnp.float32) for v in kvals])]
        # the sybil word serves BOTH attack paths in-kernel: the IHAVE
        # advert override (gated there on sc.sybil_ihave_spam) and the
        # IWANT-flood serve accrual (gated on sc.sybil_iwant_spam)
        syb_mask = (jnp.where(params.sybil, ALL, Z)
                    if sc is not None and params.sybil is not None
                    and (sc.sybil_ihave_spam or sc.sybil_iwant_spam)
                    else jnp.zeros_like(sub_all))
        with_dl = dex is not None
        blocked = []
        if with_dl:
            # round-13 delay mode: the dequeued payload slot rides as
            # one blocked [C*W, N] operand (receiver-alive masked
            # here — the kernel consumes final arrival words), the
            # handshake arrivals as pre-masked packed words; the
            # sender streams and their DMA machinery are not built.
            arr = dex["arr_pay"]
            if fmasks is not None:
                arr = arr & fmasks["alive_w"][None, None, :]
            blocked += [arr.reshape(C * W, n_pad),
                        dex["graft_arr"], dex["prune_arr"],
                        dex["retract"]]
            if track_promises:
                blocked += [dex["cheat_arr"]]
        elif sc is not None:
            blocked += [payload_bits, gossip_bits, accept_bits]
        blocked += [sub_all, params.cand_sub_bits, fanout, syb_mask,
                    would_accept, backoff_bits2, grafts, dropped,
                    mesh_sel]
        if paired:
            blocked += [sel_b["would_accept"],
                        sel_b["backoff_bits2"], sel_b["grafts"],
                        sel_b["dropped"], sel_b["mesh_sel"]]
        blocked += [seen_st, inj_st, state.backoff]
        if paired:
            blocked += [state.backoff_b]
        with_static = not params.static_score_zero
        if sc is not None:
            s0 = state.scores
            if with_static:
                blocked += [params.cand_static_score]
            blocked += [s0.first_deliveries, s0.invalid_deliveries,
                        s0.behaviour_penalty, s0.time_in_mesh]
            if paired:
                blocked += [s0.time_in_mesh_b]
            blocked += [state.iwant_serves]
            if params.cand_same_ip is not None:
                blocked += [params.cand_same_ip]
        if fmasks is not None and not with_dl:
            blocked += [fmasks["alive_w"]]
            if sc is not None and sc.sybil_iwant_spam:
                blocked += [fmasks["flood_ok"]]
        with_f = fmasks is not None and not with_dl
        # delay mode: the latency histogram is assembled in the
        # epilogue from delivered_now (the in-kernel tallies count
        # sender-stream views the delayed kernel does not hold)
        lat_b = (tel.latency_buckets
                 if tel is not None and tel.latency_hist
                 and not with_dl else 0)
        with_t = (tel is not None and (tel.counters or lat_b > 0)
                  and not with_dl)
        if lat_b:
            # latency-bucket operands: the tick's message masks (SMEM,
            # replicated on the sharded path) and the effective
            # deliver words the tallies count against
            head = head + [_telemetry.latency_bucket_masks(
                params.publish_tick, tick, lat_b, W)]
            dlv_eff = params.deliver_words
            if sc is not None:
                dlv_eff = dlv_eff & ~params.invalid_words[:, None]
            blocked += [dlv_eff]
        if shard_mesh is not None:
            # multi-chip: shard_map over the peer axis — per-shard
            # halo exchange (ICI collective-permutes) + the unmodified
            # kernel on a force-extended local plan.  Requires the
            # unpadded ring: the halos wrap at n_true, so pad lanes
            # between (d+1)S and the true ring would corrupt them.
            if n_pad != n_true:
                raise ValueError(
                    "sharded kernel path needs n_true == n_pad (no pad "
                    "lanes): pick n divisible by the block so "
                    "pad_to_block adds nothing")
            # round-14 delay lift: in delay mode the XLA enqueue
            # (delay_exchange — its true-ring rolls shard into
            # boundary collective-permutes under GSPMD) has already
            # produced final per-receiver arrival words, so the
            # sharded kernel consumes them as ordinary blocked
            # operands — no sender streams, no halo exchange.
            outs = sharded_receive(
                cfg, sc, n_true, receive_block, cdt, W,
                track_promises, receive_interpret, shard_mesh,
                shard_axis, head,
                None if with_dl else jnp.stack(ctrl_rows),
                None if with_dl else jnp.stack(fresh),
                None if with_dl else jnp.stack(adv), blocked,
                inj_st=(jnp.stack(injected)
                        if flood_bits is not None and not with_dl
                        else None),
                with_px=state.active is not None,
                with_same_ip=params.cand_same_ip is not None,
                with_static=with_static,
                ctrl2_rows=(jnp.stack(ctrl2_rows) if paired
                            else None),
                freshb_st=(jnp.stack(fresh_b) if paired else None),
                with_faults=with_f, with_telemetry=with_t,
                tel_lat_buckets=lat_b, with_knobs=with_kn,
                with_delays=with_dl)
        else:
            def flat8(rows):
                return jnp.concatenate(
                    [extend_wrap(r, n_true, n_pad, pln["p8"],
                                 pln["e8"]) for r in rows])

            def flat32(rows):
                return jnp.concatenate(
                    [extend_wrap(rows[w], n_true, n_pad,
                                 pln["p32"], pln["e32"])
                     for w in range(W)])

            if with_dl:
                flats = []      # arrivals ride blocked, not streams
            else:
                flats = [flat8(ctrl_rows)]
                if paired:
                    flats.append(flat8(ctrl2_rows))
                flats.append(flat32(fresh))
                if paired:
                    flats.append(flat32(fresh_b))
                flats.append(flat32(adv))
                if flood_bits is not None:
                    # flood-publish payload: the sender's own due
                    # publishes ride their own per-edge view
                    # (CTRL_FLOOD targets)
                    flats.append(flat32(injected))
            krn = make_receive_update(
                cfg, sc, n_true, receive_block, cdt, W,
                track_promises=track_promises,
                interpret=receive_interpret,
                with_px=state.active is not None,
                with_same_ip=params.cand_same_ip is not None,
                with_static=with_static,
                with_faults=with_f, with_telemetry=with_t,
                tel_lat_buckets=lat_b, with_knobs=with_kn,
                with_delays=with_dl)
            base0 = jnp.zeros((1,), dtype=jnp.uint32)
            outs = krn(*head, base0, *flats, *blocked)
        tel_row = None
        if with_t:
            tel_row, outs = outs[-1], outs[:-1]
        px_word = None
        if state.active is not None:
            px_word, outs = outs[-1], outs[:-1]
        it_o = iter(outs)
        new_acq = next(it_o)
        mesh_new = next(it_o)
        mesh_b_new = next(it_o) if paired else None
        backoff_new = next(it_o)
        backoff_b_new = next(it_o) if paired else None
        gates_new = tuple(
            next(it_o) for _ in range(n_gate_rows(sc is not None,
                                                  paired)))
        if sc is not None:
            fd_o, inv_o, bp_o = next(it_o), next(it_o), next(it_o)
            tim_o = next(it_o)
            tim_b_o = next(it_o) if paired else None
            iws_o = next(it_o)
        active_new = state.active
        if state.active is not None:
            # -- 4b mirror: PX-driven candidate refresh from the
            # kernel's px_rot output (received PRUNEs/PRUNE-responses),
            # then re-emit the targets gate row from the POST-rotation
            # active set — the kernel emitted it before rotation was
            # known (circular otherwise: rotation needs the kernel's
            # handshake resolution)
            if cfg.px_rotation:
                rot = px_word if neg is None else px_word | neg
                keep = mesh_new | fanout
                if paired:
                    keep = keep | mesh_b_new
                active_new = px_rotate(
                    cfg, params, active=state.active, rot=rot,
                    keep=keep, sel_k=sel_k, tick=tick,
                    salt=salt, n_stream=n_true)
            tgt_idx = 5 if sc is not None else 0
            tgt = gossip_targets_row(
                cfg, sc, params, mesh=mesh_new, fanout=fanout,
                mesh_b=mesh_b_new, active=active_new,
                gossip_row=(gates_new[1] if sc is not None else None),
                tick=tick + 1, salt=salt, n_stream=n_true, n=n_pad)
            gates_new = (gates_new[:tgt_idx] + (tgt,)
                         + gates_new[tgt_idx + 1:])
        have = state.have | new_acq
        recent = jax.lax.dynamic_update_slice_in_dim(
            state.recent, new_acq[None],
            jnp.mod(tick, cfg.history_gossip), axis=0)
        delivered_now = new_acq & params.deliver_words
        if sc is not None:
            delivered_now = delivered_now & ~params.invalid_words[:, None]
        first_tick = update_first_tick(state.first_tick, delivered_now,
                                       tick)
        scores = state.scores
        if sc is not None:
            scores = ScoreState(
                time_in_mesh=tim_o, first_deliveries=fd_o,
                mesh_deliveries=state.scores.mesh_deliveries,
                mesh_failure_penalty=state.scores.mesh_failure_penalty,
                invalid_deliveries=inv_o, behaviour_penalty=bp_o,
                time_in_mesh_b=tim_b_o)
        new_state = GossipState(
            mesh=mesh_new, fanout=fanout, last_pub=last_pub,
            backoff=backoff_new, have=have, recent=recent,
            first_tick=first_tick, scores=scores, key=state.key,
            tick=tick + 1,
            iwant_serves=(iws_o if sc is not None
                          else state.iwant_serves),
            mesh_b=mesh_b_new, backoff_b=backoff_b_new,
            active=active_new, gates=gates_new,
            gates_fp=state.gates_fp,
            inv_viol=state.inv_viol, inv_first=state.inv_first,
            pay_line=(dex["pay_line"] if with_dl else state.pay_line),
            ctrl_line=(dex["ctrl_line"] if with_dl
                       else state.ctrl_line),
            gsp_line=(dex["gsp_line"] if with_dl else state.gsp_line),
            adv_line=(dex["adv_line"] if with_dl
                      else state.adv_line),
            probe_line=(dex["probe_line"] if with_dl
                        else state.probe_line))
        if icfg is not None:
            new_state = apply_invariants(
                params, state, new_state, have_pre, rejoin_w,
                delivered_now,
                fmasks["alive_w"] if fmasks is not None else None)
        if tel is None:
            return new_state, delivered_now

        # -- telemetry frame assembly (kernel path).  The counter
        # tallies come back from the in-kernel reductions (i32, exact,
        # order-free — they equal the XLA accumulators' totals); the
        # gauge groups (mesh/scores) reduce over [:n_true] slices so
        # every float reduction sees exactly the XLA path's shapes and
        # values — the whole frame is bit-identical to the XLA step's
        # (pinned by tests/test_pallas_receive.py).
        kw_f = {}
        if tel.counters:
            def tx(bits):
                # handshake RPCs actually transmitted (the XLA
                # epilogue's tx(): nothing goes on the wire over a
                # faulted edge or toward a dead partner)
                if fmasks is None:
                    return bits
                return bits & fmasks["send_ok"] & fmasks["cand_alive"]

            graft_cnt = popcount32(tx(grafts)).sum(dtype=jnp.int32)
            prune_cnt = popcount32(tx(dropped)).sum(dtype=jnp.int32)
            if paired:
                graft_cnt = graft_cnt + popcount32(
                    tx(sel_b["grafts"])).sum(dtype=jnp.int32)
                prune_cnt = prune_cnt + popcount32(
                    tx(sel_b["dropped"])).sum(dtype=jnp.int32)
            if with_dl:
                # round-19 delay lift: the delayed kernel holds no
                # sender-stream views, so the counter halves assemble
                # in the epilogue from the SAME delay_exchange
                # products the XLA delayed step counts — identical by
                # construction (the latency_hist epilogue below set
                # the precedent).  Send-side tallies rode out of
                # delay_exchange; arrival-side counts run here
                # against this tick's possession words.
                ts = dex["tel_send"]
                af = (fmasks["alive_w"] if fmasks is not None
                      else None)
                byz_mut_k = (sc is not None and sc.byzantine_mutation
                             and params.cand_byz is not None)
                c_recv = c_srv = c_req = c_iwant_rpcs = jnp.int32(0)
                heard_k = [Z] * W
                for j in range(C):
                    byz_j = (bit_row(params.cand_byz, j)
                             if byz_mut_k else None)
                    req_c = jnp.zeros((n_pad,), dtype=jnp.int32)
                    for w in range(W):
                        got = dex["arr_pay"][j, w]
                        g_gsp = dex["arr_gsp"][j, w]
                        g_adv = dex["arr_adv"][j, w]
                        if af is not None:
                            got = got & af
                            g_gsp = g_gsp & af
                            g_adv = g_adv & af
                        ns = ~seen_st[w]
                        c_recv = c_recv + pc(got).sum(
                            dtype=jnp.int32)
                        c_srv = c_srv + pc(g_gsp & ns).sum(
                            dtype=jnp.int32)
                        req_c = req_c + pc(g_adv & ns).astype(
                            jnp.int32)
                        news = got & ns
                        if byz_j is not None:
                            news = jnp.where(byz_j, Z, news)
                        heard_k[w] = heard_k[w] | news
                    c_req = c_req + req_c.sum(dtype=jnp.int32)
                    c_iwant_rpcs = c_iwant_rpcs + (req_c > 0).sum(
                        dtype=jnp.int32)
                new_ids_k = jnp.int32(0)
                for w in range(W):
                    # subscriber gate per PEER (sub_all is the C-bit
                    # candidate gate; the heard words are 32 message
                    # bits wide)
                    new_ids_k = new_ids_k + pc(jnp.where(
                        sub_all != 0, heard_k[w], Z)).sum(
                        dtype=jnp.int32)
                c_payload = ts["payload"]
                c_ihave_rpcs = ts["ihave_rpcs"]
                c_ihave_ids = ts["ihave_ids"]
                c_dup = c_recv - new_ids_k
            else:
                sums = tel_row.sum(axis=1)      # [TEL_ROWS] i32
                c_payload = sums[TEL_PAYLOAD]
                c_ihave_rpcs = sums[TEL_IHAVE_RPCS]
                c_ihave_ids = sums[TEL_IHAVE_IDS]
                c_iwant_rpcs = sums[TEL_IWANT_RPCS]
                c_req = sums[TEL_IWANT_REQ]
                c_srv = sums[TEL_IWANT_SERVED]
                c_dup = sums[TEL_RECV] - sums[TEL_NEW_IDS]
            kw_f.update(
                payload_sent=c_payload,
                ihave_rpcs=c_ihave_rpcs,
                ihave_ids=c_ihave_ids,
                iwant_rpcs=c_iwant_rpcs,
                iwant_ids_requested=c_req,
                iwant_ids_served=c_srv,
                graft_sends=graft_cnt, prune_sends=prune_cnt,
                dup_suppressed=c_dup)
            if tel.wire:
                f32c = lambda x: x.astype(jnp.float32)  # noqa: E731
                kw_f["bytes_payload"] = (
                    f32c(c_payload + c_srv)
                    * float(ws.payload_frame))
                kw_f["bytes_control"] = (
                    f32c(c_ihave_rpcs) * float(ws.ihave_base)
                    + f32c(c_ihave_ids)
                    * float(ws.ihave_per_id)
                    + f32c(c_iwant_rpcs) * float(ws.iwant_base)
                    + f32c(c_req)
                    * float(ws.iwant_per_id)
                    + f32c(graft_cnt) * float(ws.graft_frame)
                    + f32c(prune_cnt) * float(ws.prune_frame))
        if tel.mesh or tel.degree_hist:
            deg_t = popcount32(mesh_new[:n_true])
            if paired:
                deg_t = deg_t + popcount32(mesh_b_new[:n_true])
            if tel.mesh:
                mn_d, mean_d, mx_d = _telemetry.degree_stats(
                    deg_t, params.subscribed[:n_true])
                kw_f.update(mesh_deg_min=mn_d, mesh_deg_mean=mean_d,
                            mesh_deg_max=mx_d)
            if tel.degree_hist:
                kw_f["mesh_deg_hist"] = _telemetry.degree_histogram(
                    deg_t, params.subscribed[:n_true],
                    tel.degree_buckets)
        if (tel.scores or tel.score_hist) and sc is not None:
            # start-of-tick scores — the view the gates acted on, and
            # the one telemetry group that re-reads the [C, N]
            # counters on the kernel path (the kernel's own score
            # pass runs on the UPDATED counters for next tick's gates)
            score_t = compute_scores(sc, params, state)
            mask_t = expand_bits(params.cand_sub_bits & sub_all, C)
            if tel.scores:
                sm, smn, fneg, fg = _telemetry.score_stats(
                    score_t[:, :n_true], mask_t[:, :n_true],
                    sc.gossip_threshold)
                kw_f.update(score_mean=sm, score_min=smn,
                            score_frac_neg=fneg,
                            score_frac_below_gossip=fg)
            if tel.score_hist:
                kw_f["score_hist"] = _telemetry.score_histogram(
                    score_t[:, :n_true], mask_t[:, :n_true],
                    tel.score_bucket_edges)
        if tel.latency_hist:
            if with_dl:
                # delay mode: scatter delivered_now against the
                # publish table in the epilogue — the in-kernel
                # tallies count sender-stream views the delayed
                # kernel does not hold.  Same values as the XLA
                # path's histogram by construction.
                kw_f["latency_hist"] = _telemetry.latency_histogram(
                    delivered_now, params.publish_tick, tick,
                    tel.latency_buckets)
            else:
                # in-kernel bucket tallies (rows TEL_ROWS..): exact
                # i32 counts of the same delivered-copy sets the XLA
                # path scatters in latency_histogram — equal bit for
                # bit (the sharded path psums the rows with the
                # counters)
                from ..ops.pallas.receive import TEL_ROWS
                kw_f["latency_hist"] = tel_row[TEL_ROWS:].sum(
                    axis=1, dtype=jnp.int32)
        if tel.faults and fmasks is not None:
            # unpadded masks: pad lanes are alive-with-links-up by
            # construction and must not enter the counts.  UNITS: with
            # undirected (scalar/symmetric) drops, two packed views per
            # edge — halve to undirected edge-ticks.  Under DIRECTED
            # drops the tally is in DIRECTED edge-ticks by definition:
            # each down direction counts 1, so a partition cut (both
            # directions genuinely down) counts 2 — consistent within
            # the mode, deliberately not comparable across modes.
            kw_f["down_peers"] = (~fmasks["alive_u"]).sum(
                dtype=jnp.int32)
            if fmasks["link_u"] is not None:
                kw_f["dropped_edge_ticks"] = (
                    popcount32(~fmasks["link_u"] & ALL).sum(
                        dtype=jnp.int32)
                    // (1 if params.faults.directed_drops else 2))
        return new_state, delivered_now, _telemetry.make_frame(**kw_f)

    def step(params: GossipParams, state: GossipState):
        tick = state.tick
        sub = params.subscribed            # bool [N]
        sub_all = jnp.where(sub, ALL, Z)   # uint32 [N] gate
        n = sub.shape[0]
        W = state.have.shape[0]
        # -- round-12 config-as-data (models/knobs.py): when the params
        # carry a SimKnobs pytree, every liftable protocol scalar reads
        # from its traced leaves; otherwise the static config bakes in
        # as before.  Integer compares and f32 products are value-equal
        # at the defaults, so knobbed-defaults == baked bit-identically
        # (tests/test_knobs.py pins every path).
        skn = params.sim_knobs
        K_d = skn.d if skn is not None else cfg.d
        K_d_lo = skn.d_lo if skn is not None else cfg.d_lo
        K_d_hi = skn.d_hi if skn is not None else cfg.d_hi
        K_d_score = skn.d_score if skn is not None else cfg.d_score
        K_d_out = skn.d_out if skn is not None else cfg.d_out
        K_retrans = (skn.gossip_retransmission if skn is not None
                     else cfg.gossip_retransmission)
        K_fanout_ttl = (skn.fanout_ttl_ticks if skn is not None
                        else cfg.fanout_ttl_ticks)
        kernel_on = (params.n_true is not None
                     if use_pallas_receive is None else use_pallas_receive)
        # Byzantine id-preserving payload mutation (round 11): live
        # when the config toggle AND the mutator arrays are both there
        byz_mut = (sc is not None and sc.byzantine_mutation
                   and params.cand_byz is not None)
        # -- round-13 event-driven time (models/delays.py): when the
        # params carry DelayParams, every transfer rides the K-slot
        # delay lines instead of arriving in-tick.  The named
        # capability gaps raise here (graftlint probe-refusal
        # registry): the probe's same-tick SEND/RECV reconstruction
        # and the telemetry send/receive accounting both assume the
        # one-tick-one-hop contract.
        dl = params.delays
        if dl is not None:
            if paired:
                raise NotImplementedError(_plan.MSG_DELAYS_PAIRED)
            if rpc_probe and state.probe_line is None:
                # round-20 lift: the probe is a pure readout, so the
                # snapshot's arrival leaves ride their own K-slot
                # probe line (the round-19 counter-tap move) — what
                # remains is the build requirement for that line
                raise ValueError(_plan.MSG_DELAYS_NEED_PROBE_LINE)
            if tel is not None and tel.counters:
                # round-19 lift: send-side RPC tallies count at the
                # SEND tick inside delay_exchange, receiver-side
                # tallies (recv / iwant requested+served) count at
                # ARRIVAL against the dequeued class lines — the
                # gossip observer line and the advert line carry the
                # per-class views the fused payload line merges away.
                if state.adv_line is None or state.gsp_line is None:
                    raise ValueError(
                        _plan.MSG_DELAYS_NEED_COUNTER_LINES)
            if state.pay_line is None or state.ctrl_line is None:
                raise ValueError(_plan.MSG_DELAYS_NEED_LINES)
        if kernel_on:
            if params.n_true is None:
                raise ValueError(_plan.MSG_KERNEL_NEEDS_PAD)
            # capability dispatch: faults and telemetry run IN the
            # kernel now; anything genuinely unsupported raises the
            # same message-matched refusal as before
            reason = kernel_capability(cfg, sc, params, state)
            if reason is not None:
                raise ValueError(reason)
        elif params.n_true is not None:
            raise ValueError(_plan.MSG_XLA_PADDED_STATE)
        # per-phase uniform fields from the counter-based lane hash (the
        # carried PRNG key's last word is the run seed; threefry per tick
        # would dominate the elementwise cost of the whole step).  The
        # lane stride pins the stream to the TRUE peer count so padded
        # (pallas) and unpadded (XLA) formulations draw identically.
        salt = jax.random.key_data(state.key)[-1]
        n_stream = params.n_true if params.n_true is not None else n
        u_spec = lambda phase: (C, tick, phase, salt, n_stream)  # noqa: E731

        # -- fault masks (models/faults.py): computed once per tick from
        # the compiled schedule, pure jnp.  f_alive_w gates packed
        # possession words (receiver side), f_send_ok gates per-edge
        # send masks (sender alive AND link up — symmetric drops, so
        # an edge-tick loses its payload, gossip, AND handshake RPCs in
        # both directions atomically), f_cand_alive marks candidates
        # that are up (mesh maintenance: dead edges drop with PRUNE/
        # backoff semantics, rejoin goes through the normal GRAFT path).
        fp = params.faults
        if fp is not None:
            # masks are computed on the TRUE ring (the schedule's
            # n_peers; every roll/draw wraps there) and padded
            # afterwards for the kernel path — pad peers ride as
            # alive-with-links-up, so the masks never perturb the
            # (garbage-tolerated) pad lanes and the fault stream is
            # identical between the padded and unpadded formulations
            n_tr = fp.down_start.shape[0]
            f_alive_u = _faults.alive_mask(fp, tick)        # bool [n_tr]
            f_link_u = _faults.link_ok_bits(fp, offsets, cinv, tick,
                                            n_stream)
            f_cand_alive_u = _faults.cand_alive_bits(f_alive_u, offsets)

            def fpad(a, fill):
                if a is None or n_tr == n:
                    return a
                return jnp.concatenate(
                    [a, jnp.full((n - n_tr,), fill, dtype=a.dtype)])

            f_alive = fpad(f_alive_u, True)
            f_alive_w = _faults.alive_word(f_alive)             # u32 [N]
            f_alive_all = jnp.where(f_alive, ALL, Z)
            f_cand_alive = fpad(f_cand_alive_u, jnp.uint32((1 << C) - 1))
            f_link = fpad(f_link_u, jnp.uint32((1 << C) - 1))
            f_send_ok = (f_alive_all if f_link is None
                         else f_alive_all & f_link)
            fmasks = dict(alive_w=f_alive_w, send_ok=f_send_ok,
                          cand_alive=f_cand_alive,
                          flood_ok=(f_send_ok & f_cand_alive),
                          alive_u=f_alive_u, link_u=f_link_u)
        else:
            f_alive = f_alive_w = f_alive_all = None
            f_cand_alive = f_send_ok = fmasks = None

        if icfg is not None:
            _invariants.require_armed(state, "gossipsub")

        # -- cold-restart clear (FaultSchedule.cold_restart, round 11):
        # a peer rejoining THIS tick comes back COLD — its possession
        # words and mcache ring are zeroed before anything reads them,
        # so everything it re-learns goes through the normal news path
        # (mesh forwards for fresh traffic, IHAVE->IWANT pulls for
        # anything still inside its partners' advert windows).  Shared
        # prologue: both execution paths see the cleared state.
        # ``have_pre``/``rejoin_w`` feed the invariant checker's
        # possession-monotonicity exemption.
        have_pre = state.have
        rejoin_w = None
        if fp is not None and fp.cold_restart:
            rej = fpad(_faults.rejoined_mask(fp, tick), False)
            rejoin_w = _faults.alive_word(rej)  # all-ones at rejoiners
            state = state.replace(have=state.have & ~rejoin_w,
                                  recent=state.recent & ~rejoin_w)

        # -- 0. start-of-tick gate words --------------------------------
        # Normally READ from the state: the previous tick's epilogue (or
        # the pallas kernel) emitted them while the updated counters
        # were in registers, so the prologue touches no [C, N] numeric
        # state.  A state built without gates (or pipeline_gates=False)
        # recomputes them here — bit-identical by construction.
        n_gate_rows = (5 if sc is not None else 0) + 1 \
            + (2 if paired else 1)
        if state.gates is not None and len(state.gates) != n_gate_rows:
            # a carried gate tuple from a DIFFERENT score config would
            # be silently misread row-for-row (e.g. an accept-threshold
            # word consumed as the backoff row)
            raise ValueError(
                f"state carries {len(state.gates)} gate words but this "
                f"step's config expects {n_gate_rows} — the state was "
                "built for a different score config; rebuild it or "
                "refresh_gates with the matching config")
        if (state.gates is not None and state.gates_fp is not None
                and state.gates_fp != step_gates_fp):
            # same SHAPE, different config values: the first tick would
            # silently act on gates computed under the old thresholds
            raise ValueError(
                "state's carried gates were emitted under a different "
                "(cfg, score_cfg) than this step's — refresh_gates with "
                "the new config before stepping")
        emit_gates = pipeline_gates and state.gates is not None
        g = (state.gates if emit_gates
             else compute_gates(cfg, sc, params, state, salt))
        if sc is not None:
            # packed threshold gates: bit c set iff the candidate edge
            # clears the threshold (AcceptFrom graylist gossipsub.go:584;
            # gossip/publish thresholds :610,956; graft score >= 0 :1340)
            accept_bits, gossip_bits = g[0], g[1]
            pub_ok_bits, nonneg_bits, payload_bits = g[2], g[3], g[4]
            targets = g[5]
            bo_row = g[6]
            bo_row_b = g[7] if paired else None
            if params.cand_direct is not None:
                # direct peers bypass the graylist and the gater for
                # both control and payload (AcceptFrom gossipsub.go:578)
                accept_bits = accept_bits | params.cand_direct
                payload_bits = payload_bits | params.cand_direct
            # per-word validity masks (scalar uint32 per word: bit m set
            # iff message m passes validation)
            valid_w = [~params.invalid_words[w] for w in range(W)]
        else:
            accept_bits = gossip_bits = payload_bits = None
            valid_w = None
            targets = g[0]
            bo_row = g[1]
            bo_row_b = g[2] if paired else None
        # the dense [C, N] score is only needed inside the rarely-taken
        # maintenance cond bodies (prune ranking, opportunistic-graft
        # median) — recomputed lazily there so the common path never
        # materializes it
        score_fn = ((lambda: compute_scores(sc, params, state))
                    if sc is not None else None)

        # -- 1. publish injection ---------------------------------------
        due = pack_bits(params.publish_tick == tick)            # [W]
        injected = [params.origin_words[w] & due[w] & ~state.have[w]
                    for w in range(W)]
        if fp is not None:
            # a down origin does not publish: the message is lost, not
            # deferred (the node was off at its publish tick)
            injected = [inj & f_alive_w for inj in injected]
        publishing = jnp.zeros((n,), dtype=bool)
        for w in range(W):
            publishing = publishing | (injected[w] != 0)        # [N]

        # -- 1b. fanout build/maintenance (BEFORE forwarding: the
        # reference selects fanout peers on demand at publish time,
        # gossipsub.go:961-983; TTL expiry + refill per heartbeat
        # :1505-1542).  Fanout only ever carries the owner's own
        # publishes — unsubscribed peers accept nothing to relay.
        last_pub = jnp.where(publishing, tick, state.last_pub)
        alive = (~sub) & (tick - last_pub < K_fanout_ttl)
        fanout = jnp.where(alive, state.fanout, Z)
        f_deg = popcount32(fanout)
        f_need = jnp.where(alive, K_d - f_deg, 0)
        f_elig = params.cand_sub_bits & ~fanout
        if params.cand_direct is not None:
            # direct peers receive everything anyway; spending fanout
            # slots on them would cut the effective fanout degree
            f_elig = f_elig & ~params.cand_direct
        if state.active is not None:
            f_elig = f_elig & state.active
        if params.flood_proto is not None:
            # flood-proto peers are flooded unconditionally (out_bits OR
            # below); spending fanout slots on them would cut the
            # effective gossipsub fanout degree below D
            f_elig = f_elig & ~params.cand_flood_bits
        if sc is not None:  # fanout requires score >= publish threshold
            f_elig = f_elig & pub_ok_bits
        if fp is not None:
            # dead candidates make useless fanout targets
            f_elig = f_elig & f_cand_alive
        fanout = fanout | jax.lax.cond(
            jnp.any(f_need > 0),
            lambda: sel_k(f_elig, f_need, u_spec(4)),
            lambda: jnp.zeros_like(fanout))

        # -- 2. eager forward with per-edge provenance ------------------
        # What I acquired last tick + my fresh publishes go to my mesh /
        # fanout (forwardMessage, gossipsub.go:989-999).  Honest peers
        # never forward invalid messages (validation rejects them before
        # the router sees them, validation.go:274-351); sybils do.
        # the mcache ring is ROTATING-SLOT: slot (t-1) mod Hg holds tick
        # t-1's acquisitions (the newest window); the epilogue overwrites
        # slot t mod Hg in place instead of shifting the whole ring
        # (jnp.mod, not lax.rem: tick 0 must read slot Hg-1, zeros)
        newest = jnp.mod(tick - 1, cfg.history_gossip)
        recent_new = jax.lax.dynamic_index_in_dim(
            state.recent, newest, axis=0, keepdims=False)   # [W, N]
        fresh = [recent_new[w] | injected[w] for w in range(W)]
        if sc is not None:
            fresh = [jnp.where(params.sybil, f, f & valid_w[w])
                     for w, f in enumerate(fresh)]
        if paired:
            # messages split by the SENDER's topic slot: slot-A content
            # forwards on mesh, slot-B content on mesh_b (the reference
            # forwards on the mesh of the message's topic,
            # gossipsub.go:989-999).  Unsubscribed (fanout-only) peers
            # have no meshes and send their full fresh set on the
            # slot-A/fanout path.
            fresh_a = [jnp.where(sub, f & ~params.slot_b_words[w], f)
                       for w, f in enumerate(fresh)]
            fresh_b = [f & params.slot_b_words[w]
                       for w, f in enumerate(fresh)]
        out_bits = state.mesh | fanout                          # [N]
        if params.cand_direct is not None:
            # direct peers are always eager-forward targets
            # (gossipsub.go:945-950), subscription-gated like any edge
            out_bits = out_bits | (params.cand_direct
                                   & params.cand_sub_bits)
        if params.flood_proto is not None:
            # mixed network: gossipsub peers always forward to floodsub-
            # protocol candidates, and floodsub-protocol peers flood to
            # every subscribed candidate (gossipsub.go:969-974)
            out_bits = out_bits | (params.cand_flood_bits
                                   & params.cand_sub_bits)
            # (no sub gate: an unsubscribed flood-proto peer still
            # floods its own publishes; it never holds relayed messages
            # because new_mesh_bits is gated by sub)
            out_bits = jnp.where(params.flood_proto,
                                 params.cand_sub_bits, out_bits)
        if sc is not None and sc.flood_publish:
            # own publishes additionally flood to every candidate above
            # the publish threshold (gossipsub.go:953-959)
            flood_bits = params.cand_sub_bits & pub_ok_bits
        else:
            flood_bits = None

        if (sc is not None and sc.sybil_eclipse
                and params.eclipse_sybil is not None):
            # eclipse attackers are SILENT occupiers: once inside a
            # victim's mesh they forward nothing, advertise nothing,
            # and flood nothing — the occupied slot starves the victim
            out_bits = jnp.where(params.eclipse_sybil, Z, out_bits)
            targets = jnp.where(params.eclipse_sybil, Z, targets)
            if flood_bits is not None:
                flood_bits = jnp.where(params.eclipse_sybil, Z,
                                       flood_bits)

        # rpc probe: the ATTEMPT masks are the pre-fault edge words —
        # the host exporter splits each attempted edge-tick into
        # SEND+RECV (healthy), DROP (fault-masked), or nothing (dead
        # sender) using the fault words captured alongside
        rpc_fwd_raw = out_bits if rpc_probe else None
        rpc_adv_raw = targets if rpc_probe else None
        # flood-publish sends (round 11, the fixed round-10 refusal):
        # the sender's own due publishes ride their own per-edge view
        rpc_flood_raw = flood_bits if rpc_probe else None

        if fp is not None:
            # faults cut SENDS at their source masks: a down peer (or a
            # down link's endpoint) forwards nothing, gossips nothing,
            # and flood-publishes nothing this tick.  Receivers are
            # gated at the rolled words below; the handshake transfers
            # carry the same mask inside raw_transfers.
            out_bits = out_bits & f_send_ok
            targets = targets & f_send_ok
            if flood_bits is not None:
                flood_bits = flood_bits & f_send_ok

        have_start = state.have
        seen = [have_start[w] | injected[w] for w in range(W)]
        fd_add = [None] * C         # per-receiver-bit popcounts (int32 [N])
        md_new = [None] * C
        inv_add = [None] * C

        def acc(a, b):
            return b if a is None else a + b

        # -- 3a. lazy gossip advertisement + targets --------------------
        # (selected before forwarding so phases 2+3 can share one roll
        # per edge below; this block reads only pre-maintenance state,
        # the same inputs the separate phase-3 loop consumed)
        # advertise ids seen in the last HistoryGossip windows; targets =
        # random non-mesh subscribed candidates, max(Dlazy, factor*elig),
        # both sides above the gossip threshold (gossipsub.go:1656-1712)
        adv = []
        for w in range(W):
            aw = injected[w]
            for h in range(cfg.history_gossip):
                aw = aw | state.recent[h, w]
            if sc is not None:
                aw = jnp.where(params.sybil, aw, aw & valid_w[w])
            adv.append(aw)
        # targets arrive as a gate row (compute_gates row 5/0) — the
        # selection runs in the emission epilogue where mesh/fanout and
        # the gossip gate are already live.
        # Promise withholding is BEHAVIORAL from here on: the P7 broken-
        # promise penalty is derived from advertised-vs-delivered traffic
        # at the receiver (gossip_tracer.go:48-153 + applyIwantPenalties
        # gossipsub.go:1566-1571), not from the sybil flag — a stealthy
        # spammer (promise_break) accrues it identically.
        withhold = None
        if sc is not None and sc.sybil_ihave_spam:
            withhold = params.sybil
        if sc is not None and params.promise_break is not None:
            withhold = (params.promise_break if withhold is None
                        else withhold | params.promise_break)

        # -- 3b. IWANT-flood defense (mcache.go:66-80, gossipsub.go:
        # 690-693; attack: gossipsub_spam_test.go:24) is ALWAYS-ON when
        # scoring is: the per-edge serve ledger updates in the score
        # epilogue (phase 5), where the receiver-side provenance
        # popcounts it reuses are live — see the iwant_serves update
        # there.  Honest and attacked runs share that code path, as in
        # the reference's unconditional mcache transmission tally.
        iwant_serves = state.iwant_serves

        # -- heartbeat maintenance SELECTIONS (gossipsub.go:1299-1552).
        # Read-only on start-of-tick state (score, mesh, backoff,
        # uniforms), so they run before forwarding and are shared by the
        # two execution paths (XLA transfer rolls / pallas kernel) that
        # diverge below.  Parameterized over the topic slot: paired-
        # topic mode runs one full maintenance pass per topic's
        # mesh/backoff with decorrelated uniform phases, exactly as the
        # reference heartbeat loops over topics (gossipsub.go:1299).
        mesh_before = state.mesh

        def maintain(mesh0, bo_row0, ph_graft, ph_prune, ph_og):
            dead = None
            if fp is not None:
                # churn: edges to dead candidates — and a dead peer's
                # own whole mesh — drop with PRUNE/backoff semantics
                # (folded into ``dropped`` below).  BOTH ends start the
                # same backoff clock at the death tick, so a rejoining
                # peer and its old partners become mutually graftable
                # again at the same heartbeat and rejoin rides the
                # normal deg < Dlo GRAFT path.
                dead = mesh0 & ~(f_cand_alive & f_alive_all)
                mesh0 = mesh0 & ~dead
            if sc is not None:
                # drop negative-score mesh members first (:1332)
                neg = mesh0 & ~nonneg_bits
                mesh_ng = mesh0 & nonneg_bits
            else:
                neg = None
                mesh_ng = mesh0
            deg = popcount32(mesh_ng)                           # [N]

            # graft up to D when deg < Dlo (gossipsub.go:1340-1360);
            # candidates need score >= 0 in v1.1.  in_backoff is the
            # only per-edge numeric state — its packed comparison
            # arrives as a gate row (compute_gates: row 6 scored /
            # row 1 unscored; row 7/2 for slot B in paired mode)
            backoff_bits = bo_row0
            can_graft = (params.cand_sub_bits & ~mesh_ng & ~backoff_bits
                         & sub_all)
            if params.cand_direct is not None:
                # never GRAFT at a direct peer (gossipsub.go:1340-1345)
                can_graft = can_graft & ~params.cand_direct
            if state.active is not None:
                can_graft = can_graft & state.active
            if params.flood_proto is not None:
                # floodsub-protocol peers have no mesh: never graft at
                # them, and they graft at nobody
                can_graft = can_graft & ~params.cand_flood_bits
                can_graft = jnp.where(params.flood_proto, Z, can_graft)
            if sc is not None:
                can_graft = can_graft & nonneg_bits
            if fp is not None:
                # no grafting AT dead candidates, and no maintenance BY
                # a dead peer
                can_graft = can_graft & f_cand_alive & f_alive_all
            need = jnp.where(deg < K_d_lo, K_d - deg, 0)
            grafts = jax.lax.cond(
                jnp.any(need > 0),
                lambda: sel_k(can_graft, need, u_spec(ph_graft)),
                lambda: jnp.zeros_like(mesh_ng))

            # prune down to D when deg > Dhi.  v1.0: random retention;
            # v1.1: keep the Dscore best by score, then at least Dout
            # outbound, random fill to D (gossipsub.go:1376-1435).
            over = deg > K_d_hi

            def compute_prunes():
                if sc is None:
                    keep = sel_k(mesh_ng, jnp.full_like(deg, K_d),
                                 u_spec(ph_prune))
                else:
                    score = score_fn()
                    rnd = lane_uniform((C, n), tick, ph_prune, salt,
                                       stride=n_stream)
                    top = select_k_by_priority_bits(
                        mesh_ng, score, jnp.full_like(deg, K_d_score),
                        tiebreak=rnd)
                    n_out_top = popcount32(top & OUT_MASK)
                    need_out = jnp.maximum(0, K_d_out - n_out_top)
                    out_keep = select_k_by_priority_bits(
                        mesh_ng & ~top & OUT_MASK, rnd, need_out)
                    taken = top | out_keep
                    n_taken = popcount32(taken)
                    fill = select_k_by_priority_bits(
                        mesh_ng & ~taken, rnd,
                        jnp.maximum(K_d - n_taken, 0))
                    keep = taken | fill
                return mesh_ng & ~keep & jnp.where(over, ALL, Z)

            prunes = jax.lax.cond(jnp.any(over), compute_prunes,
                                  lambda: jnp.zeros_like(mesh_ng))

            if sc is not None:
                # opportunistic grafting: when the mesh's median score
                # sags below the threshold, graft extra high-scoring
                # peers (gossipsub.go:1467-1498).  Runs 1-in-
                # opportunistic_graft_ticks, so the median rank-compare
                # sits under the cond too.
                do_og = (tick % sc.opportunistic_graft_ticks) == 0

                def compute_og():
                    # median = the mesh bit at ascending rank deg//2 =
                    # descending rank C-1-deg//2 (non-mesh bits pinned
                    # to +inf rank first); rank-compare, not a sort
                    score = score_fn()
                    in_mesh = expand_bits(mesh_ng, C)
                    mesh_rank = ranks_desc(
                        jnp.where(in_mesh, score, jnp.inf))
                    med_pick = in_mesh & (mesh_rank
                                          == (C - 1 - deg // 2)[None, :])
                    median = jnp.where(
                        deg > 0,
                        jnp.where(med_pick, score, 0.0).sum(0), 0.0)
                    og_row = ((median < sc.opportunistic_graft_threshold)
                              & sub)
                    og_elig = (can_graft & ~grafts
                               & pack_rows(score > median[None, :]))
                    og_need = jnp.where(
                        og_row, sc.opportunistic_graft_peers, 0)
                    return sel_k(og_elig, og_need, u_spec(ph_og))

                grafts = grafts | jax.lax.cond(
                    do_og, compute_og, lambda: jnp.zeros_like(mesh_ng))

            if sc is not None and sc.sybil_graft_flood:
                # GRAFT-flooding sybils re-graft every tick, ignoring
                # their own backoff (gossipsub_spam_test.go:349)
                grafts = jnp.where(params.sybil,
                                   params.cand_sub_bits & ~mesh_ng,
                                   grafts)
            if (sc is not None and sc.sybil_eclipse
                    and params.eclipse_sybil is not None):
                # eclipse formation (round 11): attackers coordinate
                # GRAFT pressure on the VICTIM set — every tick, at
                # every subscribed victim candidate, ignoring their
                # own backoff.  Re-grafting during backoff accrues P7
                # at the victim (the defense this attack tests).
                grafts = jnp.where(
                    params.eclipse_sybil,
                    params.cand_victim_bits & params.cand_sub_bits
                    & ~mesh_ng,
                    grafts)
            if fp is not None:
                # safety net over the overrides above: not even a
                # graft-flooding sybil grafts while dead or at the dead
                grafts = grafts & f_cand_alive & f_alive_all

            mesh_sel = (mesh_ng | grafts) & ~prunes
            dropped = prunes if neg is None else prunes | neg
            if dead is not None:
                dropped = dropped | dead
            backoff_bits2 = backoff_bits | dropped  # post-write backoff
            # bits, derived algebraically (the only edges whose backoff
            # changed are prunes|neg, all set beyond tick)
            would_accept = sub_all & ~backoff_bits2
            if params.cand_direct is not None:
                # GRAFT from a direct peer is rejected with a PRUNE
                # response (gossipsub.go:737-745) — the A-mask carries
                # the rejection back in the same transfer round
                would_accept = would_accept & ~params.cand_direct
            if params.flood_proto is not None:
                would_accept = jnp.where(params.flood_proto, Z,
                                         would_accept)
            if sc is not None:
                would_accept = would_accept & nonneg_bits
                a_sent = would_accept | ~accept_bits
            else:
                a_sent = would_accept
            return dict(grafts=grafts, dropped=dropped, neg=neg,
                        mesh_sel=mesh_sel, backoff_bits2=backoff_bits2,
                        would_accept=would_accept, a_sent=a_sent)

        sel_a = maintain(state.mesh, bo_row, 2, 3, 5)
        sel_b = (maintain(state.mesh_b, bo_row_b, 12, 13, 15)
                 if paired else None)
        grafts, dropped = sel_a["grafts"], sel_a["dropped"]
        mesh_sel, backoff_bits2 = sel_a["mesh_sel"], sel_a["backoff_bits2"]
        would_accept, a_sent = sel_a["would_accept"], sel_a["a_sent"]

        # -- round-13 event-driven exchange (models/delays.py).  This
        # tick's sends — exactly the pre-delay send words, gated at
        # SEND time — roll toward their receivers and enqueue into the
        # K-slot delay lines at slot (t + d - 1) mod K, d sampled per
        # directed edge-tick; the tick's ARRIVALS dequeue from slot
        # t mod K (d = 1 transfers pass straight through, which is why
        # DelayConfig(1, 0, 1) is bit-identical to the pre-delay
        # step).  Shared by the XLA paths and the kernel dispatch so
        # the two can never drift.
        def delay_exchange(split: bool):
            K = dl.k_slots
            M1 = jnp.uint32(0xFFFFFFFF)
            nt = params.n_true

            def roll_t(x, off):
                # circulant rolls wrap at the TRUE ring on padded
                # (kernel-path) states; pad lanes carry zeros
                if nt is None or nt == n:
                    return jnp.roll(x, off, axis=0)
                return jnp.concatenate(
                    [jnp.roll(x[:nt], off, axis=0), x[nt:]])

            def transfer_t(bits, pair=False):
                # the module-level edge-duality transfer, wrapping at
                # the true ring on padded states
                return transfer_bits(bits, cfg, pair=pair, n_true=nt)

            d_edge = _delays.edge_delays(dl, (C, n), tick,
                                         stride=n_stream)
            slot_sel = _delays.slot_select_words(d_edge, tick, K)
            cheat_raw = (jnp.where(withhold, targets, Z)
                         if withhold is not None else None)

            # ---- payload/gossip send words (SEND-time gating) ------
            send_gsp = (targets if withhold is None
                        else jnp.where(withhold, Z, targets))
            if not split and sc is not None:
                # combined form: the receiver's packed payload∧gossip
                # gates travel to the sender as one pair transfer
                open_word = ALL | (ALL << jnp.uint32(16))
                gate_recv = jax.lax.cond(
                    jnp.all((payload_bits & gossip_bits) == ALL),
                    lambda: jnp.full_like(payload_bits, open_word),
                    lambda: transfer_t(
                        payload_bits
                        | ((payload_bits & gossip_bits)
                           << jnp.uint32(16)), pair=True))
                send_fwd = out_bits & gate_recv
                send_gsp = send_gsp & (gate_recv >> jnp.uint32(16))
                send_flood = (flood_bits & gate_recv
                              if flood_bits is not None else None)
            else:
                send_fwd, send_flood = out_bits, flood_bits

            # ---- send-side counter tallies (round-19 lift): payload
            # copies and IHAVE ids/RPCs count at the SEND tick from
            # the very pre-roll words the enqueue closures build
            # (popcount is roll-invariant), so K=1 equals the
            # pre-delay sender-side accounting bit for bit.  Advert
            # counting uses ``targets`` PRE-withhold, the documented
            # convention: a withholding spammer does advertise.
            tel_send = None
            if tel is not None and tel.counters:
                t0 = jnp.int32(0)
                tel_send = dict(payload=t0, ihave_ids=t0,
                                ihave_rpcs=t0)
                adv_any = jnp.zeros((n,), dtype=bool)
                for w in range(W):
                    adv_any = adv_any | (adv[w] != 0)
                for c_send in range(C):
                    m_adv = bit_row(targets, c_send)
                    tel_send["ihave_rpcs"] += (
                        m_adv & adv_any).sum(dtype=jnp.int32)
                    m_f = bit_row(send_fwd, c_send)
                    m_fl = (bit_row(send_flood, c_send)
                            if send_flood is not None else None)
                    for w in range(W):
                        pay_w = jnp.where(m_f, fresh[w], Z)
                        if m_fl is not None:
                            pay_w = pay_w | jnp.where(
                                m_fl, injected[w], Z)
                        tel_send["payload"] += pc(pay_w).sum(
                            dtype=jnp.int32)
                        tel_send["ihave_ids"] += pc(
                            jnp.where(m_adv, adv[w], Z)).sum(
                            dtype=jnp.int32)

            # ---- enqueue: roll each edge's fused (or per-class)
            # word and route it to its sampled slot ------------------
            def enqueue_edges(line, word_of):
                """OR per-(slot, edge, word) contributions into a
                [K, C, W, N] line; ``word_of(c_send, w)`` returns the
                ROLLED, receiver-gated word for that edge."""
                if W == 0:
                    return line
                adds = [[[None] * W for _ in range(C)]
                        for _ in range(K)]
                for c_send, off in enumerate(offsets):
                    j = cinv[c_send]
                    sel_j = [jnp.where(bit_row(slot_sel[s], j), M1, Z)
                             for s in range(K)]
                    for w in range(W):
                        rolled = word_of(c_send, off, j, w)
                        for s in range(K):
                            adds[s][j][w] = rolled & sel_j[s]
                return line | jnp.stack(
                    [jnp.stack([jnp.stack(aw) for aw in ac])
                     for ac in adds])

            if not split:
                def fused_word(c_send, off, j, w):
                    m_f = bit_row(send_fwd, c_send)
                    m_g = bit_row(send_gsp, c_send)
                    sent = (jnp.where(m_f, fresh[w], Z)
                            | jnp.where(m_g, adv[w], Z))
                    if send_flood is not None:
                        sent = sent | jnp.where(
                            bit_row(send_flood, c_send), injected[w],
                            Z)
                    return roll_t(sent, off)

                pay_line = enqueue_edges(state.pay_line, fused_word)
                arr_pay, pay_line = _delays.line_dequeue(pay_line,
                                                         tick)
                if tel_send is not None:
                    # gossip-class OBSERVER line: the same post-gate
                    # advert words fused_word ORs into pay_line, kept
                    # separate so iwant_served sees the class
                    # provenance the merge destroys.  Possession never
                    # reads it.
                    def obs_gsp_word(c_send, off, j, w):
                        return roll_t(jnp.where(
                            bit_row(send_gsp, c_send), adv[w], Z),
                            off)

                    gsp_line = enqueue_edges(state.gsp_line,
                                             obs_gsp_word)
                    arr_gsp, gsp_line = _delays.line_dequeue(gsp_line,
                                                             tick)
                else:
                    gsp_line = state.gsp_line
                    arr_gsp = None
            else:
                # split form: mesh/eager and gossip classes keep their
                # own lines (P3 needs the arrival provenance); the
                # receiver gate words apply at enqueue, post-roll —
                # the same values the pre-delay split loops produced
                def mesh_word(c_send, off, j, w):
                    sent = jnp.where(bit_row(send_fwd, c_send),
                                     fresh[w], Z)
                    if send_flood is not None:
                        sent = sent | jnp.where(
                            bit_row(send_flood, c_send), injected[w],
                            Z)
                    rolled = roll_t(sent, off)
                    if sc is not None:
                        rolled = jnp.where(bit_row(payload_bits, j),
                                           rolled, Z)
                    return rolled

                def gsp_word(c_send, off, j, w):
                    sent = jnp.where(bit_row(send_gsp, c_send),
                                     adv[w], Z)
                    rolled = roll_t(sent, off)
                    if sc is not None:
                        rolled = jnp.where(
                            bit_row(payload_bits & gossip_bits, j),
                            rolled, Z)
                    return rolled

                pay_line = enqueue_edges(state.pay_line, mesh_word)
                gsp_line = enqueue_edges(state.gsp_line, gsp_word)
                arr_pay, pay_line = _delays.line_dequeue(pay_line,
                                                         tick)
                arr_gsp, gsp_line = _delays.line_dequeue(gsp_line,
                                                         tick)

            # ---- advert observer line (round-19 lift): the rolled
            # IHAVE advert words, carried so iwant_requested counts
            # against the RECEIVER's possession at the ARRIVAL tick.
            # Combined convention: ungated (pre-withhold targets);
            # split convention: the receiver's payload∧gossip gate
            # applies post-roll at enqueue, as the pre-delay split
            # gossip loop gated r_adv.
            adv_line, arr_adv = state.adv_line, None
            if tel_send is not None:
                def adv_word_of(c_send, off, j, w):
                    rolled = roll_t(jnp.where(
                        bit_row(targets, c_send), adv[w], Z), off)
                    if split and sc is not None:
                        rolled = jnp.where(
                            bit_row(payload_bits & gossip_bits, j),
                            rolled, Z)
                    return rolled

                adv_line = enqueue_edges(state.adv_line, adv_word_of)
                arr_adv, adv_line = _delays.line_dequeue(adv_line,
                                                         tick)

            # ---- control enqueue + dequeue -------------------------
            if fp is not None:
                grafts_tx = grafts & f_send_ok
                dropped_tx = dropped & f_send_ok
            else:
                grafts_tx, dropped_tx = grafts, dropped
            graft_fly = transfer_t(grafts_tx)
            prune_fly = transfer_t(dropped_tx)
            cheat_fly = None
            if cheat_raw is not None:
                # broken-promise adverts: gossip-gated at SEND like
                # real gossip (the receiver only IWANTs accepted
                # adverts), indexed at the receiver after transfer
                cheat_fly = transfer_t(cheat_raw)
                if sc is not None:
                    cheat_fly = cheat_fly & payload_bits & gossip_bits
            R = state.ctrl_line.shape[1]
            zrow = jnp.zeros_like(graft_fly)
            ctrl_line = state.ctrl_line | jnp.stack(
                [jnp.stack([graft_fly & slot_sel[s],
                            prune_fly & slot_sel[s], zrow]
                           + ([cheat_fly & slot_sel[s]]
                              if R == 4 else []))
                 for s in range(K)])
            arr_ctrl, ctrl_line = _delays.line_dequeue(ctrl_line,
                                                       tick)
            graft_raw = arr_ctrl[0]
            prune_arr = arr_ctrl[1]
            retr_arr = arr_ctrl[2]
            cheat_arr = arr_ctrl[3] if R == 4 else None
            if fp is not None:
                # a down peer processes no inbound control
                graft_raw = graft_raw & f_alive_all
                prune_arr = prune_arr & f_alive_all
                retr_arr = retr_arr & f_alive_all
                if cheat_arr is not None:
                    cheat_arr = cheat_arr & f_alive_all
            if sc is not None:
                # graylisted peers' control traffic dropped outright
                # at ARRIVAL (AcceptFrom); the retraction leg is a
                # PRUNE-response and is not graylist-gated, as in the
                # pre-delay resolve
                graft_arr = graft_raw & accept_bits
                prune_arr = prune_arr & accept_bits
            else:
                graft_arr = graft_raw

            # ---- handshake resolution at ARRIVAL + the delayed
            # negative-acknowledgment second leg ---------------------
            violation = graft_arr & backoff_bits2
            accept = graft_arr & would_accept
            conf = a_sent
            if fp is not None:
                # an unsendable confirmation counts as a rejection
                # (the grafter's confirm window times out)
                conf = conf & f_send_ok
            retr_src = graft_raw & ~conf
            retr_fly = transfer_t(retr_src)
            d1_bits = pack_rows(d_edge == 1)
            retract = retr_fly & d1_bits
            retr_later = retr_fly & ~d1_bits
            ctrl_line = ctrl_line | jnp.stack(
                [jnp.stack([zrow, zrow, retr_later & slot_sel[s]]
                           + ([zrow] if R == 4 else []))
                 for s in range(K)])
            if fp is not None:
                # a failed local write is known immediately (the
                # connection write errored) and a dead grafter
                # processes no inbound retraction
                retract = (retract & f_alive_all) | (grafts
                                                     & ~f_send_ok)
            retract = retract | retr_arr

            # ---- probe line (round-20 lift): the three send-class
            # attempt masks ride their own observer line, receiver-
            # indexed like the ctrl rows, so the probe snapshot can
            # place RECVs at the true arrival tick.  Post-fault sends
            # only (a fault-cut RPC never enters the network); pure
            # readout — possession never reads the dequeue.
            probe_line, arr_probe = state.probe_line, None
            if rpc_probe and state.probe_line is not None:
                fwd_fly = transfer_t(out_bits)
                adv_fly = transfer_t(targets)
                flood_fly = (transfer_t(flood_bits)
                             if flood_bits is not None
                             else jnp.zeros_like(fwd_fly))
                probe_line = state.probe_line | jnp.stack(
                    [jnp.stack([fwd_fly & slot_sel[s],
                                adv_fly & slot_sel[s],
                                flood_fly & slot_sel[s]])
                     for s in range(K)])
                arr_probe, probe_line = _delays.line_dequeue(
                    probe_line, tick)

            return dict(arr_pay=arr_pay, arr_gsp=arr_gsp,
                        pay_line=pay_line, gsp_line=gsp_line,
                        ctrl_line=ctrl_line, graft_arr=graft_arr,
                        prune_arr=prune_arr, retract=retract,
                        cheat_arr=cheat_arr, violation=violation,
                        accept=accept, tel_send=tel_send,
                        arr_adv=arr_adv, adv_line=adv_line,
                        probe_line=probe_line, arr_probe=arr_probe)

        rpc_snap = None
        if rpc_probe:
            if params.flood_proto is not None:
                raise NotImplementedError(_plan.MSG_PROBE_MIXED_PROTOCOL)

            def stk(rows):
                return (jnp.stack(rows) if W
                        else jnp.zeros((0, n), dtype=jnp.uint32))

            # everything the host exporter needs to reconstruct the
            # per-RPC streams: attempt masks + content words + fault
            # words (all-healthy constants when no schedule rides).
            # Pure readout — nothing below consumes it.
            rpc_snap = dict(
                fwd=rpc_fwd_raw, ihave=rpc_adv_raw,
                graft=grafts, prune=dropped,
                flood=(rpc_flood_raw if rpc_flood_raw is not None
                       else jnp.zeros((n,), dtype=jnp.uint32)),
                inj=stk(injected),
                withhold=(withhold if withhold is not None
                          else jnp.zeros((n,), dtype=bool)),
                send_ok=(f_send_ok if fp is not None
                         else jnp.full((n,), ALL)),
                alive=(f_alive if fp is not None
                       else jnp.ones((n,), dtype=bool)),
                fresh=stk(fresh), adv=stk(adv), seen=stk(seen))
            if paired:
                # round 13 (the lifted refusal): the SLOT-B attempt
                # masks and the slot-split payload words, so the
                # exporter can emit per-slot GRAFT/PRUNE topics and
                # split each edge's payload/IHAVE by topic slot
                fwd_b_raw = state.mesh_b
                if params.cand_direct is not None:
                    fwd_b_raw = fwd_b_raw | (params.cand_direct
                                             & params.cand_sub_bits)
                if (sc is not None and sc.sybil_eclipse
                        and params.eclipse_sybil is not None):
                    fwd_b_raw = jnp.where(params.eclipse_sybil, Z,
                                          fwd_b_raw)
                rpc_snap.update(
                    fwd_b=fwd_b_raw,
                    graft_b=sel_b["grafts"], prune_b=sel_b["dropped"],
                    fresh_a=stk(fresh_a), fresh_b=stk(fresh_b))

        if kernel_on:
            # PX rotation folds in BOTH slots' negative-score drops
            # (XLA 4b does the same)
            neg_px = sel_a["neg"]
            if paired and sel_b["neg"] is not None:
                neg_px = (sel_b["neg"] if neg_px is None
                          else neg_px | sel_b["neg"])
            dex_k = (delay_exchange(split=False) if dl is not None
                     else None)
            if rpc_probe and dex_k is not None:
                # round-20 lift: arrival-side masks dequeued from the
                # probe/ctrl lines, so the exporter can place RECVs
                rpc_snap.update(
                    arr_fwd=dex_k["arr_probe"][0],
                    arr_ihave=dex_k["arr_probe"][1],
                    arr_flood=dex_k["arr_probe"][2],
                    arr_graft=dex_k["graft_arr"],
                    arr_prune=dex_k["prune_arr"])
            outk = _finish_kernel(
                dex=dex_k,
                params=params, state=state, fanout=fanout,
                last_pub=last_pub, injected=injected,
                fresh=(fresh_a if paired else fresh),
                adv=adv, targets=targets, withhold=withhold,
                out_bits=out_bits,
                grafts=grafts, dropped=dropped, mesh_sel=mesh_sel,
                a_sent=a_sent, would_accept=would_accept,
                backoff_bits2=backoff_bits2, sub_all=sub_all,
                payload_bits=payload_bits, gossip_bits=gossip_bits,
                accept_bits=accept_bits, valid_w=valid_w, tick=tick,
                salt=salt, flood_bits=flood_bits, neg=neg_px,
                sel_b=sel_b,
                fresh_b=(fresh_b if paired else None),
                fmasks=fmasks, have_pre=have_pre, rejoin_w=rejoin_w)
            if rpc_probe:
                outk = (*outk, rpc_snap)
            return outk

        # behavioral broken-promise detection: a withholding peer's
        # IHAVE claims ids the receiver doesn't hold (the reference
        # attack advertises bogus ids, gossipsub_spam_test.go:135); the
        # receiver IWANTs what it lacks, nothing arrives, and it counts
        # one P7 unit for the edge that tick (gossip_tracer.go:48-153 +
        # applyIwantPenalties) — derived from traffic, not the flag
        cheat_src = (jnp.where(withhold, targets, Z)
                     if withhold is not None else None)
        broken_add = [None] * C
        lack_any = None
        if cheat_src is not None:
            # the receiver lacks SOME advertised id (bogus ids lie
            # outside its possession set almost surely)
            lack_any = jnp.zeros((n,), dtype=bool)
            for w in range(W):
                lack_any = lack_any | ((~seen[w]) != 0)
            if fp is not None:
                # a down receiver got no advert, so it records no
                # broken promise this tick
                lack_any = lack_any & f_alive

        # -- telemetry counter accumulators (models/telemetry.py).
        # Sender-side counts (payload copies, IHAVE ids) are popcounts
        # of the very send words the loops below already build;
        # receiver-side counts (IWANT requested/served, duplicates)
        # need a gossip-only re-roll per edge-word — the main
        # observation cost, measured as the on-vs-off bench delta.
        # Advert counting uses ``targets`` PRE-withhold: a withholding
        # spammer does advertise (that is the attack), so its ids land
        # in ihave_ids/iwant_ids_requested but never in
        # iwant_ids_served — the gap is the broken-promise traffic.
        tel_acc = None
        if tel is not None and tel.counters:
            z32 = jnp.int32(0)
            tel_acc = dict(payload=z32, recv=z32, ihave_rpcs=z32,
                           ihave_ids=z32, iwant_rpcs=z32, req=z32,
                           srv=z32)
            tel_adv_any = jnp.zeros((n,), dtype=bool)
            for w in range(W):
                tel_adv_any = tel_adv_any | (adv[w] != 0)

        # Columns are independent: every same-tick deliverer of a new
        # message gets delivery credit (the reference's near-first window
        # covers simultaneous copies, score.go:684-818; with one tick =
        # one heartbeat, same-tick ties ARE the window — and crediting all
        # of them avoids biasing credit by candidate-bit order).
        # force_split pins the split loops for equivalence testing: the
        # two formulations must produce identical possession/mesh
        # trajectories (credit-policy differences are documented above).
        combined = (C <= 16 and (sc is None or not sc.track_p3)
                    and not force_split)
        dex = None
        if dl is not None:
            if not combined and state.gsp_line is None:
                raise ValueError(_plan.MSG_DELAYS_NEED_SPLIT_LINE)
            dex = delay_exchange(split=not combined)
            if rpc_snap is not None:
                # round-20 lift: arrival-side masks dequeued from the
                # probe/ctrl lines, so the exporter can place RECVs
                rpc_snap.update(
                    arr_fwd=dex["arr_probe"][0],
                    arr_ihave=dex["arr_probe"][1],
                    arr_flood=dex["arr_probe"][2],
                    arr_graft=dex["graft_arr"],
                    arr_prune=dex["prune_arr"])
            if tel_acc is not None:
                # sender-side tallies counted at the SEND tick inside
                # delay_exchange; the arrival loops below add the
                # receiver-side halves against THIS tick's possession
                for k_send in ("payload", "ihave_ids", "ihave_rpcs"):
                    tel_acc[k_send] += dex["tel_send"][k_send]
        if dex is not None and combined:
            # -- 2+3 delayed (round 13): this tick's sends went into
            # the delay line inside delay_exchange; what remains is
            # the ARRIVAL half of the old fused loop — news split,
            # Byzantine rejection, and the per-edge P2/P4 provenance
            # counts — over the dequeued slot.
            heard = [Z] * W
            for j in range(C):
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                fd_j = iv_j = None
                req_c = None
                for w in range(W):
                    got = dex["arr_pay"][j, w]
                    if fp is not None:
                        got = got & f_alive_w  # down peers hear 0
                    news = got & ~seen[w]
                    if tel_acc is not None:
                        # receiver-side tallies at ARRIVAL: duplicates
                        # against this tick's possession, served ids
                        # from the gossip observer line, requested ids
                        # from the advert line (both fault-masked like
                        # the payload arrivals)
                        g_gsp = dex["arr_gsp"][j, w]
                        g_adv = dex["arr_adv"][j, w]
                        if fp is not None:
                            g_gsp = g_gsp & f_alive_w
                            g_adv = g_adv & f_alive_w
                        tel_acc["recv"] += pc(got).sum(
                            dtype=jnp.int32)
                        tel_acc["srv"] += pc(g_gsp & ~seen[w]).sum(
                            dtype=jnp.int32)
                        req_c = acc(req_c,
                                    pc(g_adv & ~seen[w]).astype(
                                        jnp.int32))
                    if sc is not None:
                        news = jax.lax.optimization_barrier(news)
                    news_bad = None
                    if byz_j is not None:
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    heard[w] = heard[w] | news
                    if sc is not None:
                        fd_j = acc(fd_j, pc(news & valid_w[w]))
                        iv_j = acc(iv_j, pc(news & ~valid_w[w]))
                        if news_bad is not None:
                            iv_j = iv_j + pc(news_bad)
                fd_add[j], inv_add[j] = fd_j, iv_j
                if tel_acc is not None and req_c is not None:
                    tel_acc["req"] += req_c.sum(dtype=jnp.int32)
                    tel_acc["iwant_rpcs"] += (req_c > 0).sum(
                        dtype=jnp.int32)
                if dex["cheat_arr"] is not None:
                    broken_add[j] = (bit_row(dex["cheat_arr"], j)
                                     & lack_any)
            new_heard_bits = [jnp.where(sub, hw, Z) for hw in heard]
        elif dex is not None:
            # -- delayed SPLIT loops: mesh/eager and gossip arrivals
            # keep their class provenance through separate lines (P3
            # counts duplicate mesh copies at ARRIVAL)
            mesh_heard = [Z] * W
            for j in range(C):
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                fd_j = md_j = iv_j = None
                for w in range(W):
                    got = dex["arr_pay"][j, w]
                    if fp is not None:
                        got = got & f_alive_w
                    news = got & ~seen[w]
                    if tel_acc is not None:
                        tel_acc["recv"] += pc(got).sum(
                            dtype=jnp.int32)
                    news_bad = None
                    if byz_j is not None:
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    mesh_heard[w] = mesh_heard[w] | news
                    if sc is not None:
                        fd_j = acc(fd_j, pc(news & valid_w[w]))
                        if sc.track_p3:
                            md_ok = (got if byz_j is None
                                     else jnp.where(byz_j, Z, got))
                            md_j = acc(md_j, pc(md_ok & valid_w[w]
                                                & ~have_start[w]))
                        iv_j = acc(iv_j, pc(news & ~valid_w[w]))
                        if news_bad is not None:
                            iv_j = iv_j + pc(news_bad)
                fd_add[j], md_new[j], inv_add[j] = fd_j, md_j, iv_j
            seen_g = [seen[w] | mesh_heard[w] for w in range(W)]
            gossip_heard = [Z] * W
            for j in range(C):
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                req_c = None
                for w in range(W):
                    got = dex["arr_gsp"][j, w]
                    if fp is not None:
                        got = got & f_alive_w
                    news = got & ~seen_g[w]
                    if tel_acc is not None:
                        # requested/served count against START-of-tick
                        # possession (~seen, not ~seen_g), the same
                        # estimator the pre-delay split loops used
                        g_adv = dex["arr_adv"][j, w]
                        if fp is not None:
                            g_adv = g_adv & f_alive_w
                        tel_acc["recv"] += pc(got).sum(
                            dtype=jnp.int32)
                        tel_acc["srv"] += pc(got & ~seen[w]).sum(
                            dtype=jnp.int32)
                        req_c = acc(req_c,
                                    pc(g_adv & ~seen[w]).astype(
                                        jnp.int32))
                    news_bad = None
                    if byz_j is not None:
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    gossip_heard[w] = gossip_heard[w] | news
                    if sc is not None:
                        fd_add[j] = acc(fd_add[j],
                                        pc(news & valid_w[w]))
                        inv_add[j] = acc(inv_add[j],
                                         pc(news & ~valid_w[w]))
                        if news_bad is not None:
                            inv_add[j] = inv_add[j] + pc(news_bad)
                if tel_acc is not None and req_c is not None:
                    tel_acc["req"] += req_c.sum(dtype=jnp.int32)
                    tel_acc["iwant_rpcs"] += (req_c > 0).sum(
                        dtype=jnp.int32)
                if dex["cheat_arr"] is not None:
                    broken_add[j] = (bit_row(dex["cheat_arr"], j)
                                     & lack_any)
            new_heard_bits = [
                jnp.where(sub, mesh_heard[w] | gossip_heard[w], Z)
                for w in range(W)]
        elif combined:
            # -- 2+3 fused: ONE roll per edge carries the eager-forward,
            # flood-publish, AND lazy-gossip payloads.  The receiver-side
            # score gates (payload at graylist, payload∧gossip at gossip
            # threshold — gossipsub.go:584,610) travel to the sender as
            # one packed pair-transfer, so gating happens before the roll
            # and the rolled word needs no receiver-side mask.  Rolls
            # dominate the step (tools/profile_ablate.py: ~1/3 of it), so
            # halving the payload rolls is the single biggest win.  Falls
            # back to the split loops when P3 bookkeeping needs the
            # mesh/gossip provenance distinction, or when C > 16.
            # Credit-policy note: the split gossip loop denies credit to a
            # gossip edge whose message was mesh-delivered the SAME tick
            # (news vs seen|mesh_heard); here both deliverers are
            # credited, uniformly extending the documented all-same-tick-
            # deliverers P2/P4 policy (module docstring, Known deviation).
            send_gsp = (targets if withhold is None
                        else jnp.where(withhold, Z, targets))
            send_cheat = cheat_src
            send_fwd_b = state.mesh_b if paired else None
            if paired and params.cand_direct is not None:
                # direct peers are eager-forward targets on EVERY topic
                # (gossipsub.go:945-950): slot-B fresh content reaches
                # them too (slot A rides out_bits, which already
                # includes the direct word)
                send_fwd_b = send_fwd_b | (params.cand_direct
                                           & params.cand_sub_bits)
            if paired and fp is not None:
                # slot-B forwards are sends too (out_bits carried the
                # slot-A mask only)
                send_fwd_b = send_fwd_b & f_send_ok
            if (paired and sc is not None and sc.sybil_eclipse
                    and params.eclipse_sybil is not None):
                # eclipse attackers are silent on the slot-B mesh too
                send_fwd_b = jnp.where(params.eclipse_sybil, Z,
                                       send_fwd_b)
            if sc is not None:
                # with every edge's payload AND gossip gate open (no
                # attackers, no graylisting — the clean steady state)
                # the pair transfer of the packed gates is a transfer
                # of all-ones: skip the C rolls and use the constant
                open_word = ALL | (ALL << jnp.uint32(16))
                gate_recv = jax.lax.cond(
                    jnp.all((payload_bits & gossip_bits) == ALL),
                    lambda: jnp.full_like(payload_bits, open_word),
                    lambda: transfer_bits(
                        payload_bits
                        | ((payload_bits & gossip_bits)
                           << jnp.uint32(16)), cfg, pair=True))
                send_fwd = out_bits & gate_recv
                if paired:
                    send_fwd_b = send_fwd_b & gate_recv
                send_gsp = send_gsp & (gate_recv >> jnp.uint32(16))
                if send_cheat is not None:
                    # the receiver only IWANTs (and so only records a
                    # broken promise for) adverts it accepts: same
                    # gossip-threshold gate as real gossip
                    send_cheat = send_cheat & (gate_recv >> jnp.uint32(16))
                send_flood = (flood_bits & gate_recv
                              if flood_bits is not None else None)
            else:
                send_fwd, send_flood = out_bits, flood_bits
            heard = [Z] * W
            for c_send, off in enumerate(offsets):
                j = cinv[c_send]    # receiver-side bit for this edge
                m_f = bit_row(send_fwd, c_send)                 # [N]
                m_g = bit_row(send_gsp, c_send)
                m_fb = (bit_row(send_fwd_b, c_send) if paired else None)
                m_fl = (bit_row(send_flood, c_send)
                        if send_flood is not None else None)
                m_adv = (bit_row(targets, c_send)
                         if tel_acc is not None else None)
                # receiver-side view: is MY candidate j (this edge's
                # sender) a Byzantine mutator?
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                fd_j = iv_j = None
                req_c = None
                for w in range(W):
                    fwd_w = jnp.where(m_f,
                                      fresh_a[w] if paired else fresh[w],
                                      Z)
                    if paired:
                        fwd_w = fwd_w | jnp.where(m_fb, fresh_b[w], Z)
                    if m_fl is not None:
                        fwd_w = fwd_w | jnp.where(m_fl, injected[w], Z)
                    gsp_w = jnp.where(m_g, adv[w], Z)
                    # same value as the old fused (fwd | gossip) word —
                    # uint32 OR is associative, so splitting it for the
                    # telemetry tallies changes nothing downstream
                    sent = fwd_w | gsp_w
                    rolled = jnp.roll(sent, off, axis=0)
                    if fp is not None:
                        rolled = rolled & f_alive_w  # down peers hear 0
                    news = rolled & ~seen[w]
                    if tel_acc is not None:
                        adv_w = jnp.where(m_adv, adv[w], Z)
                        r_gsp = jnp.roll(gsp_w, off, axis=0)
                        r_adv = jnp.roll(adv_w, off, axis=0)
                        if fp is not None:
                            r_gsp = r_gsp & f_alive_w
                            r_adv = r_adv & f_alive_w
                        tel_acc["payload"] += pc(fwd_w).sum(
                            dtype=jnp.int32)
                        tel_acc["ihave_ids"] += pc(adv_w).sum(
                            dtype=jnp.int32)
                        tel_acc["srv"] += pc(r_gsp & ~seen[w]).sum(
                            dtype=jnp.int32)
                        tel_acc["recv"] += pc(rolled).sum(
                            dtype=jnp.int32)
                        req_c = acc(req_c,
                                    pc(r_adv & ~seen[w]).astype(
                                        jnp.int32))
                    if sc is not None:
                        # barrier: force ONE materialization of this
                        # edge's news word.  Without it XLA fuses the
                        # roll separately into the heard-OR chain AND
                        # into each provenance-popcount fusion,
                        # recomputing every roll twice (profiler:
                        # ~1.2 ms/tick of duplicated pad chains at 1M)
                        news = jax.lax.optimization_barrier(news)
                    news_bad = None
                    if byz_j is not None:
                        # Byzantine mutation: every copy this sender
                        # relays/serves reaches the validator with
                        # corrupted content — it is REJECTED (never
                        # acquired, so an honest copy from another
                        # edge can still land) and accrues the
                        # per-edge P4 invalid-delivery penalty
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    heard[w] = heard[w] | news
                    if sc is not None:
                        # P2/P4 credit new-message deliverers, eager and
                        # gossip alike (later-tick copies are dropped at
                        # the seen-cache, pubsub.go:851-868)
                        fd_j = acc(fd_j, pc(news & valid_w[w]))
                        iv_j = acc(iv_j, pc(news & ~valid_w[w]))
                        if news_bad is not None:
                            iv_j = iv_j + pc(news_bad)
                if send_cheat is not None:
                    got_cheat = jnp.roll(bit_row(send_cheat, c_send),
                                         off, axis=0)
                    broken_add[j] = got_cheat & lack_any
                if tel_acc is not None:
                    tel_acc["ihave_rpcs"] += (m_adv & tel_adv_any).sum(
                        dtype=jnp.int32)
                    if req_c is not None:    # stays None when W == 0
                        tel_acc["req"] += req_c.sum(dtype=jnp.int32)
                        tel_acc["iwant_rpcs"] += (req_c > 0).sum(
                            dtype=jnp.int32)
                fd_add[j], inv_add[j] = fd_j, iv_j
            new_heard_bits = [jnp.where(sub, hw, Z) for hw in heard]
        else:
            # -- 2. eager forward with per-edge provenance --------------
            mesh_heard = [Z] * W
            for c_send, off in enumerate(offsets):
                j = cinv[c_send]    # receiver-side bit for this edge
                mask_c = bit_row(out_bits, c_send)              # [N]
                ok_j = (bit_row(payload_bits, j) if sc is not None
                        else None)
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                fd_j = md_j = iv_j = None
                for w in range(W):
                    sent = jnp.where(mask_c, fresh[w], Z)
                    if flood_bits is not None:
                        sent = sent | jnp.where(
                            bit_row(flood_bits, c_send), injected[w], Z)
                    rolled = jnp.roll(sent, off, axis=0)
                    if ok_j is not None:
                        rolled = jnp.where(ok_j, rolled, Z)
                    if fp is not None:
                        rolled = rolled & f_alive_w  # down peers hear 0
                    news = rolled & ~seen[w]
                    news_bad = None
                    if byz_j is not None:
                        # Byzantine mutation: rejected at validation —
                        # P4 accrues, nothing is acquired (see the
                        # combined path)
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    mesh_heard[w] = mesh_heard[w] | news
                    if tel_acc is not None:
                        tel_acc["payload"] += pc(sent).sum(
                            dtype=jnp.int32)
                        tel_acc["recv"] += pc(rolled).sum(
                            dtype=jnp.int32)
                    if sc is not None:
                        # P3 counts duplicate copies from mesh members in
                        # the window — the provenance that forces the
                        # split loops
                        fd_j = acc(fd_j, pc(news & valid_w[w]))
                        if sc.track_p3:
                            md_ok = (rolled if byz_j is None
                                     else jnp.where(byz_j, Z, rolled))
                            md_j = acc(md_j, pc(md_ok & valid_w[w]
                                                & ~have_start[w]))
                        iv_j = acc(iv_j, pc(news & ~valid_w[w]))
                        if news_bad is not None:
                            iv_j = iv_j + pc(news_bad)
                fd_add[j], md_new[j], inv_add[j] = fd_j, md_j, iv_j

            # -- 3. lazy gossip exchange --------------------------------
            seen_g = [seen[w] | mesh_heard[w] for w in range(W)]
            gossip_heard = [Z] * W
            for c_send, off in enumerate(offsets):
                j = cinv[c_send]
                adv_mask = bit_row(targets, c_send)
                send_mask = adv_mask
                if withhold is not None:
                    send_mask = send_mask & ~withhold
                ok_j = None
                if sc is not None:
                    ok_j = bit_row(payload_bits & gossip_bits, j)
                byz_j = bit_row(params.cand_byz, j) if byz_mut else None
                req_c = None
                for w in range(W):
                    sent = jnp.where(send_mask, adv[w], Z)
                    rolled = jnp.roll(sent, off, axis=0)
                    if ok_j is not None:
                        rolled = jnp.where(ok_j, rolled, Z)
                    if fp is not None:
                        rolled = rolled & f_alive_w  # down peers hear 0
                    news = rolled & ~seen_g[w]
                    news_bad = None
                    if byz_j is not None:
                        # mutated IWANT serves: rejected, P4, never
                        # acquired (see the combined path)
                        news_bad = jnp.where(byz_j, news, Z)
                        news = news & ~news_bad
                    gossip_heard[w] = gossip_heard[w] | news
                    if tel_acc is not None:
                        # requested/served count against START-of-tick
                        # possession (~seen, not ~seen_g): the same
                        # estimator the combined path uses, so the
                        # byte/ratio outputs are formulation-invariant
                        # (pinned by test_telemetry.py)
                        adv_w = jnp.where(adv_mask, adv[w], Z)
                        r_adv = jnp.roll(adv_w, off, axis=0)
                        if ok_j is not None:
                            r_adv = jnp.where(ok_j, r_adv, Z)
                        if fp is not None:
                            r_adv = r_adv & f_alive_w
                        tel_acc["ihave_ids"] += pc(adv_w).sum(
                            dtype=jnp.int32)
                        tel_acc["srv"] += pc(rolled & ~seen[w]).sum(
                            dtype=jnp.int32)
                        tel_acc["recv"] += pc(rolled).sum(
                            dtype=jnp.int32)
                        req_c = acc(req_c,
                                    pc(r_adv & ~seen[w]).astype(
                                        jnp.int32))
                    if sc is not None:
                        # IWANT-pulled messages go through validation
                        # like any other delivery: P2 valid, P4 invalid
                        fd_add[j] = fd_add[j] + pc(news & valid_w[w])
                        inv_add[j] = inv_add[j] + pc(news & ~valid_w[w])
                        if news_bad is not None:
                            inv_add[j] = inv_add[j] + pc(news_bad)
                if cheat_src is not None:
                    got_cheat = jnp.roll(bit_row(cheat_src, c_send),
                                         off, axis=0)
                    if ok_j is not None:
                        got_cheat = got_cheat & ok_j
                    broken_add[j] = got_cheat & lack_any
                if tel_acc is not None:
                    tel_acc["ihave_rpcs"] += (adv_mask
                                              & tel_adv_any).sum(
                        dtype=jnp.int32)
                    if req_c is not None:    # stays None when W == 0
                        tel_acc["req"] += req_c.sum(dtype=jnp.int32)
                        tel_acc["iwant_rpcs"] += (req_c > 0).sum(
                            dtype=jnp.int32)
            new_heard_bits = [
                jnp.where(sub, mesh_heard[w] | gossip_heard[w], Z)
                for w in range(W)]

        new_acquired = (jnp.stack(
            [new_heard_bits[w] | injected[w]
             for w in range(W)], axis=0) if W
            else jnp.zeros((0, n), dtype=jnp.uint32))           # [W, N]
        have = state.have | new_acquired
        # rotating-slot ring write: overwrite slot t mod Hg in place
        # (lowers to an in-place dynamic-update inside the scan; the
        # old full-ring concatenate shift re-wrote every slot per tick)
        recent = jax.lax.dynamic_update_slice_in_dim(
            state.recent, new_acquired[None],
            jnp.mod(tick, cfg.history_gossip), axis=0)

        delivered_now = new_acquired & params.deliver_words
        if sc is not None:
            delivered_now = delivered_now & ~params.invalid_words[:, None]
        first_tick = update_first_tick(state.first_tick, delivered_now,
                                       tick)

        # -- 4. apply maintenance + handshake (XLA transfer path) -------
        # handshake: partner accepts GRAFT unless unsubscribed, backed
        # off, or (v1.1) negative-scored (handleGraft gossipsub.go:713-
        # 804); PRUNE always removes + backs off (handlePrune :806-838).
        # Negative-score prunes notify the partner too (the reference
        # sends PRUNE for every mesh removal, gossipsub.go:1332-1338).
        #
        # The PRUNE-response round trip is folded into the SAME transfer
        # pass: each side ships a "no PRUNE would come back" mask
        # A = would-accept | would-silently-drop (a graylisted GRAFT is
        # ignored without a PRUNE response, AcceptFrom gossipsub.go:584),
        # so the grafter keeps exactly the edges the old explicit
        # reject-back retraction kept — bit-identical, one transfer round
        # (C rolls) and one serial dependency shorter.
        def raw_transfers(sel, skip_a=False):
            grafts_s, dropped_s = sel["grafts"], sel["dropped"]
            if fp is not None:
                # handshake RPCs are sends like any other: a dead peer
                # (or a down link) transmits no GRAFT/PRUNE/A this tick.
                # The local effects of ``dropped`` (mesh removal, own
                # backoff) still apply — only the notification is lost,
                # as when the reference's PRUNE RPC is dropped.
                grafts_tx = grafts_s & f_send_ok
                dropped_tx = dropped_s & f_send_ok
                a_tx = sel["a_sent"] & f_send_ok
            else:
                grafts_tx, dropped_tx = grafts_s, dropped_s
                a_tx = sel["a_sent"]

            def live():
                if C <= 16:
                    # GRAFT+PRUNE masks ride one pair-packed transfer,
                    # the A mask a second (2C rolls; was 3C with
                    # reject-back)
                    recv = transfer_bits(
                        grafts_tx | (dropped_tx << jnp.uint32(16)), cfg,
                        pair=True)
                    graft_recv = recv & ALL
                    prune_recv = recv >> jnp.uint32(16)
                else:
                    graft_recv = transfer_bits(grafts_tx, cfg)
                    prune_recv = transfer_bits(dropped_tx, cfg)
                a_recv = (jnp.zeros_like(grafts_s) if skip_a
                          else transfer_bits(a_tx, cfg))
                return graft_recv, prune_recv, a_recv

            def idle():
                z = jnp.zeros_like(grafts_s)
                return z, z, z

            # steady state: NOBODY grafted or dropped this tick, so the
            # handshake transfers carry nothing — graft/prune receives
            # are zero and retract = grafts & ~a_recv is zero for any
            # a_recv value, making the zero stand-in exact
            graft_recv, prune_recv, a_recv = jax.lax.cond(
                jnp.any((grafts_s | dropped_s) != 0), live, idle)
            return graft_recv, prune_recv, (None if skip_a else a_recv)

        def resolve(sel, graft_recv, prune_recv, a_recv):
            if fp is not None:
                # a down peer processes no inbound control either
                graft_recv = graft_recv & f_alive_all
                prune_recv = prune_recv & f_alive_all
                a_recv = a_recv & f_alive_all
            if sc is not None:
                # graylisted peers' control traffic is dropped outright
                graft_recv = graft_recv & accept_bits
                prune_recv = prune_recv & accept_bits
            violation = graft_recv & sel["backoff_bits2"]
            accept = graft_recv & sel["would_accept"]
            retract = sel["grafts"] & ~a_recv  # partner would PRUNE back
            # retract LAST: when accept and retract coincide on an edge
            # (possible only under sybil_graft_flood, whose grafts
            # bypass the grafter's own backoff check) the PRUNE response
            # wins, as in the explicit reject-back form (handlePrune)
            mesh_new = ((sel["mesh_sel"] | accept) & ~prune_recv
                        ) & ~retract
            bo_trig = sel["dropped"] | prune_recv | retract
            # PRUNE receipt and PRUNE-responses both carry PX records
            # in the reference (gossipsub.go:856-937)
            return mesh_new, bo_trig, violation, prune_recv | retract

        if dex is not None:
            # delayed handshake (round 13): arrivals were resolved at
            # dequeue time in delay_exchange — the same accept /
            # violation / retraction algebra as resolve(), evaluated
            # against the ARRIVAL tick's state, with the rejection
            # round trip riding the ctrl line as a delayed retraction
            mesh = ((mesh_sel | dex["accept"]) & ~dex["prune_arr"]
                    ) & ~dex["retract"]
            bo_trigger = dropped | dex["prune_arr"] | dex["retract"]
            backoff_violation = dex["violation"]
            px_rot = dex["prune_arr"] | dex["retract"]
            mesh_b_new = violation_b = None
        elif not paired:
            mesh, bo_trigger, backoff_violation, px_rot = resolve(
                sel_a, *raw_transfers(sel_a))
            mesh_b_new = violation_b = None
        else:
            # cross-slot routing: the topic p calls slot X lives in the
            # PARTNER's other slot on edges whose offset is an odd
            # multiple of T/2 (class(p+o) = class(p) + T/2), so control
            # received from the sender's slot A pertains to MY slot B
            # there.  Edge parity is static; bit c and its partner bit
            # cinv[c] share it (o and -o are congruent mod T).
            even = jnp.uint32(sum(
                1 << c_ for c_, o_ in enumerate(offsets)
                if (o_ % cfg.n_topics) == 0))
            odd = ~even & ALL
            ga, pa, _ = raw_transfers(sel_a, skip_a=True)
            gb, pb, _ = raw_transfers(sel_b, skip_a=True)
            # both slots' A masks ride ONE pair-packed transfer
            # (paired mode enforces C <= 16); skipped when neither slot
            # grafted (retract = grafts & ~a is zero regardless).
            # Each half is masked to the C candidate bits BEFORE
            # packing: the scored a_sent carries ~accept_bits, whose
            # bits >= 16 would otherwise pollute the slot-B half and
            # silently disable every slot-B-informed retraction
            # (caught by the kernel-parity suite, which transfers the
            # per-slot A bits individually and retracts correctly)
            a_ok = ALL if fp is None else (ALL & f_send_ok)
            a_both = jax.lax.cond(
                jnp.any((sel_a["grafts"] | sel_b["grafts"]) != 0),
                lambda: transfer_bits(
                    (sel_a["a_sent"] & a_ok)
                    | ((sel_b["a_sent"] & a_ok) << jnp.uint32(16)),
                    cfg, pair=True),
                lambda: jnp.zeros_like(sel_a["grafts"]))
            aa = a_both & ALL
            ab = a_both >> jnp.uint32(16)
            mesh, bo_trigger, backoff_violation, px_a = resolve(
                sel_a, (ga & even) | (gb & odd),
                (pa & even) | (pb & odd), (aa & even) | (ab & odd))
            mesh_b_new, bo_trigger_b, violation_b, px_b = resolve(
                sel_b, (gb & even) | (ga & odd),
                (pb & even) | (pa & odd), (ab & even) | (aa & odd))
            px_rot = px_a | px_b

        # -- 4b. PX-driven candidate refresh (gossipsub.go:856-937).
        # A received PRUNE (or PRUNE-response) carries peer-exchange
        # records; the pruned peer drops that address from its active
        # set and dials a fresh candidate from the pool instead —
        # modeling topology recovery after mass pruning.  Edges still
        # in any mesh/fanout are never deactivated.
        active_new = state.active
        if state.active is not None and cfg.px_rotation:
            # rotation triggers: received PRUNEs / PRUNE-responses (the
            # PX carriers) AND our own negative-score drops — after
            # cutting a misbehaving peer, its address slot is re-filled
            # from the pool (the connector dialing PX-learned addresses,
            # gossipsub.go:1594-1616)
            rot = px_rot
            if sel_a["neg"] is not None:
                rot = rot | sel_a["neg"]
            if paired and sel_b["neg"] is not None:
                rot = rot | sel_b["neg"]
            keep = mesh | fanout
            if paired:
                keep = keep | mesh_b_new
            active_new = px_rotate(
                cfg, params, active=state.active, rot=rot, keep=keep,
                sel_k=sel_k, tick=tick, salt=salt, n_stream=n_stream)

        # -- 5. score counter updates + decay ---------------------------
        # (array-level on purpose: a row-wise variant was measured 1.7x
        # slower — [C, N] row slices read whole (sublane, 128) tiles)
        # backoff as remaining ticks: dropped edges restart the clock at
        # B-1 (gossipsub.go:1332-1338; blocked for ticks t+1..t+B-1,
        # free at t+B — identical to the absolute-expiry form); PRUNE
        # receipt / retraction takes max(existing, B-1) — the overwrite,
        # since remaining never exceeds B-1
        bo16 = (jnp.int16(cfg.backoff_ticks - 1) if skn is None
                else (skn.backoff_ticks - 1).astype(jnp.int16))

        def bo_update(bo_old, trig):
            dec = jnp.maximum(bo_old - jnp.int16(1), jnp.int16(0))
            return jnp.where(expand_bits(trig, C), bo16, dec)

        backoff = bo_update(state.backoff, bo_trigger)
        backoff_b = (bo_update(state.backoff_b, bo_trigger_b)
                     if paired else None)

        scores = state.scores
        if sc is not None:
            s0 = state.scores
            cdt = jnp.dtype(sc.counter_dtype)
            f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
            zcn = jnp.zeros((C, n), dtype=jnp.float32)
            # provenance counts are <= 32*W per edge-tick: stage the
            # [C, N] stacks through u8 when that fits (4x less
            # concatenate traffic than u32; exact — counts are small
            # integers either way)
            cnt_dt = jnp.uint8 if W * 32 <= 255 else jnp.uint32
            fd_stack = (jnp.stack([r.astype(cnt_dt) for r in fd_add],
                                  axis=0).astype(jnp.float32)
                        if W else zcn)
            iv_stack = (jnp.stack([r.astype(cnt_dt) for r in inv_add],
                                  axis=0).astype(jnp.float32)
                        if W else zcn)
            # -- 3b (cont.): gossip-repair serve ledger, ALWAYS-ON.
            # Pulls over an edge = the same receiver-side news counts
            # that feed P2/P4 (ids newly received this tick; in the
            # combined path eager-forward copies tally too — a
            # conservative deviation, the budget only sees MORE load).
            # Decay matches mcache expiry: ceil-div by HistoryLength
            # (plain s//H stalls below H and would leave phantom load
            # after a flood stops).
            s32 = state.iwant_serves.astype(jnp.int32)
            pulls = (fd_stack + iv_stack).astype(jnp.int32)
            if sc.sybil_iwant_spam and params.sybil is not None:
                # sybils re-request their partner's FULL advertised
                # window every tick (gossipsub_spam_test.go:24); the
                # partner serves until the per-edge budget
                # (GossipRetransmission x window ids, mcache.go:66-80 +
                # gossipsub.go:690-693) is spent, then ignores that
                # peer's IWANTs — the retransmission cutoff.
                adv_count = None
                for w in range(W):
                    pcw = pc(adv[w])
                    adv_count = (pcw if adv_count is None
                                 else adv_count + pcw)
                partner_adv = jnp.stack(
                    [jnp.roll(adv_count, -off) for off in offsets])
                budget = K_retrans * partner_adv
                flood = jnp.where((s32 < budget) & (partner_adv > 0),
                                  partner_adv, 0)
                if fp is not None:
                    # no IWANT flood over a faulted edge: a dead sybil
                    # requests nothing, a dead (or link-cut) partner
                    # serves nothing
                    flood = jnp.where(
                        expand_bits(f_send_ok & f_cand_alive, C),
                        flood, 0)
                pulls = jnp.where(params.sybil[None, :], flood, pulls)
            decayed = s32 - (s32 + cfg.history_length - 1
                             ) // cfg.history_length
            iwant_serves = jnp.clip(decayed + pulls, 0,
                                    30000).astype(jnp.int16)
            in_mesh_after = expand_bits(mesh, C)
            fd = jnp.minimum(f32(s0.first_deliveries) + fd_stack,
                             sc.first_message_deliveries_cap)
            inv = f32(s0.invalid_deliveries) + iv_stack
            if sc.track_p3:
                in_mesh_before = expand_bits(mesh_before, C)
                md_stack = (jnp.stack([r.astype(cnt_dt) for r in md_new],
                                      axis=0).astype(jnp.float32)
                            if W else zcn)
                md = jnp.minimum(
                    f32(s0.mesh_deliveries) + md_stack * in_mesh_before,
                    sc.mesh_message_deliveries_cap)
                # P3b: an edge pruned while active with a delivery deficit
                # keeps the deficit² as a sticky penalty (score.go Prune)
                removed = in_mesh_before & ~in_mesh_after
                was_active = (f32(s0.time_in_mesh)
                              > sc.mesh_message_deliveries_activation)
                deficit = jnp.maximum(
                    0.0, sc.mesh_message_deliveries_threshold - md)
                mfp = f32(s0.mesh_failure_penalty) + jnp.where(
                    removed & was_active, deficit * deficit, 0.0)
            # P7: backoff violations + broken gossip promises
            # (per-topic violations each count, gossipsub.go:747-765)
            bp = f32(s0.behaviour_penalty) + expand_bits(
                backoff_violation, C).astype(jnp.float32)
            if paired:
                bp = bp + expand_bits(violation_b, C).astype(jnp.float32)
            if cheat_src is not None:
                # one P7 unit per edge per tick with >= 1 broken promise
                # (applyIwantPenalties adds per-peer counts once per
                # heartbeat; magnitudes calibrated the same way)
                broken = jnp.stack(
                    [jnp.zeros((n,), dtype=bool) if broken_add[j] is None
                     else broken_add[j] != 0 for j in range(C)])
                bp = bp + broken.astype(jnp.float32)

            # decay (refreshScores, score.go:495-556); storage may be
            # bf16 — the math runs f32, the write casts back
            def dk(x, decay, dtype=cdt):
                x = x * decay
                return jnp.where(x < sc.decay_to_zero, 0.0, x).astype(dtype)

            scores = ScoreState(
                time_in_mesh=jnp.where(
                    in_mesh_after,
                    jnp.minimum(s0.time_in_mesh + 1, 32766),
                    0).astype(jnp.int16),
                first_deliveries=dk(fd, sc.first_message_deliveries_decay),
                mesh_deliveries=(dk(md, sc.mesh_message_deliveries_decay)
                                 if sc.track_p3 else s0.mesh_deliveries),
                mesh_failure_penalty=(
                    dk(mfp, sc.mesh_failure_penalty_decay)
                    if sc.track_p3 else s0.mesh_failure_penalty),
                invalid_deliveries=dk(
                    inv, sc.invalid_message_deliveries_decay),
                behaviour_penalty=dk(bp, sc.behaviour_penalty_decay,
                                     dtype=jnp.dtype(sc.bp_dtype)),
                time_in_mesh_b=(jnp.where(
                    expand_bits(mesh_b_new, C),
                    jnp.minimum(s0.time_in_mesh_b + 1, 32766),
                    0).astype(jnp.int16) if paired else None),
            )

        new_state = GossipState(
            mesh=mesh, fanout=fanout, last_pub=last_pub, backoff=backoff,
            have=have, recent=recent, first_tick=first_tick, scores=scores,
            key=state.key, tick=tick + 1, iwant_serves=iwant_serves,
            mesh_b=mesh_b_new, backoff_b=backoff_b, active=active_new,
            gates=state.gates, gates_fp=state.gates_fp,
            inv_viol=state.inv_viol, inv_first=state.inv_first,
            pay_line=(dex["pay_line"] if dex is not None
                      else state.pay_line),
            ctrl_line=(dex["ctrl_line"] if dex is not None
                       else state.ctrl_line),
            gsp_line=(dex["gsp_line"] if dex is not None
                      else state.gsp_line),
            adv_line=(dex["adv_line"] if dex is not None
                      else state.adv_line),
            probe_line=(dex["probe_line"] if dex is not None
                        else state.probe_line))
        if state.gates is not None:
            # emit the NEXT tick's gate words now, while the updated
            # counters are live in registers (XLA fuses the score math
            # and packs into the decay pass) — the next prologue then
            # reads G words/peer instead of the [C, N] numeric state.
            # Emitted even with pipeline_gates=False (whose prologue
            # recomputes rather than trusting the carry): the returned
            # state must never hold STALE gates that a later pipelined
            # step would silently act on.
            new_state = new_state.replace(gates=compute_gates(
                cfg, sc, params, new_state, salt))
        if icfg is not None:
            new_state = apply_invariants(
                params, state, new_state, have_pre, rejoin_w,
                delivered_now, f_alive_w)
        if tel is None:
            if rpc_probe:
                return new_state, delivered_now, rpc_snap
            return new_state, delivered_now

        # -- telemetry frame assembly (models/telemetry.py): a pure
        # READOUT of values the tick already computed, so the state
        # trajectory is bit-identical to the telemetry-free step.
        kw_f = {}
        if tel_acc is not None:
            def tx(bits):
                # handshake RPCs actually transmitted: a dead peer or a
                # cut link sends nothing (the masking raw_transfers
                # applies), and nothing goes on the wire TOWARD a dead
                # partner either — the reference drops the connection,
                # it does not send a PRUNE RPC at a dead peer.  The
                # partner-alive mask matters for prunes only (sel
                # 'dropped' includes the fault-injected dead edges;
                # graft selection already excludes dead candidates) —
                # without it churn ticks would tally one phantom PRUNE
                # per dead mesh edge into the control-byte estimate.
                if fp is None:
                    return bits
                return bits & f_send_ok & f_cand_alive

            graft_cnt = popcount32(tx(sel_a["grafts"])).sum(
                dtype=jnp.int32)
            prune_cnt = popcount32(tx(sel_a["dropped"])).sum(
                dtype=jnp.int32)
            if paired:
                graft_cnt = graft_cnt + popcount32(
                    tx(sel_b["grafts"])).sum(dtype=jnp.int32)
                prune_cnt = prune_cnt + popcount32(
                    tx(sel_b["dropped"])).sum(dtype=jnp.int32)
            new_ids = jnp.int32(0)
            for w in range(W):
                new_ids = new_ids + pc(new_heard_bits[w]).sum(
                    dtype=jnp.int32)
            kw_f.update(
                payload_sent=tel_acc["payload"],
                ihave_rpcs=tel_acc["ihave_rpcs"],
                ihave_ids=tel_acc["ihave_ids"],
                iwant_rpcs=tel_acc["iwant_rpcs"],
                iwant_ids_requested=tel_acc["req"],
                iwant_ids_served=tel_acc["srv"],
                graft_sends=graft_cnt, prune_sends=prune_cnt,
                dup_suppressed=tel_acc["recv"] - new_ids)
            if tel.wire:
                f32c = lambda x: x.astype(jnp.float32)  # noqa: E731
                kw_f["bytes_payload"] = (
                    f32c(tel_acc["payload"] + tel_acc["srv"])
                    * float(ws.payload_frame))
                kw_f["bytes_control"] = (
                    f32c(tel_acc["ihave_rpcs"]) * float(ws.ihave_base)
                    + f32c(tel_acc["ihave_ids"])
                    * float(ws.ihave_per_id)
                    + f32c(tel_acc["iwant_rpcs"]) * float(ws.iwant_base)
                    + f32c(tel_acc["req"]) * float(ws.iwant_per_id)
                    + f32c(graft_cnt) * float(ws.graft_frame)
                    + f32c(prune_cnt) * float(ws.prune_frame))
        if tel.mesh or tel.degree_hist:
            deg_t = popcount32(mesh)
            if paired:
                deg_t = deg_t + popcount32(mesh_b_new)
            if tel.mesh:
                mn_d, mean_d, mx_d = _telemetry.degree_stats(deg_t, sub)
                kw_f.update(mesh_deg_min=mn_d, mesh_deg_mean=mean_d,
                            mesh_deg_max=mx_d)
            if tel.degree_hist:
                kw_f["mesh_deg_hist"] = _telemetry.degree_histogram(
                    deg_t, sub, tel.degree_buckets)
        if (tel.scores or tel.score_hist) and sc is not None:
            # start-of-tick scores — the same view the gates acted on
            score_t = score_fn()
            mask_t = expand_bits(params.cand_sub_bits & sub_all, C)
            if tel.scores:
                sm, smn, fneg, fg = _telemetry.score_stats(
                    score_t, mask_t, sc.gossip_threshold)
                kw_f.update(score_mean=sm, score_min=smn,
                            score_frac_neg=fneg,
                            score_frac_below_gossip=fg)
            if tel.score_hist:
                kw_f["score_hist"] = _telemetry.score_histogram(
                    score_t, mask_t, tel.score_bucket_edges)
        if tel.latency_hist:
            kw_f["latency_hist"] = _telemetry.latency_histogram(
                delivered_now, params.publish_tick, tick,
                tel.latency_buckets)
        if tel.faults and fp is not None:
            kw_f["down_peers"] = (~f_alive).sum(dtype=jnp.int32)
            if f_link is not None:
                # UNITS: undirected mode halves the two views per edge;
                # directed mode counts DIRECTED edge-ticks (a partition
                # cut = 2: both directions are down) — see the kernel
                # frame site
                kw_f["dropped_edge_ticks"] = (
                    popcount32(~f_link & ALL).sum(dtype=jnp.int32)
                    // (1 if fp.directed_drops else 2))
        frame = _telemetry.make_frame(**kw_f)
        if rpc_probe:
            return new_state, delivered_now, frame, rpc_snap
        return new_state, delivered_now, frame

    return step


def make_fused_window(cfg: GossipSimConfig,
                      score_cfg: ScoreSimConfig | None = None, *,
                      ticks_fused: int = 8,
                      receive_block: int = 8192,
                      receive_interpret: bool = False,
                      telemetry: _telemetry.TelemetryConfig | None = None,
                      shard_mesh=None, shard_axis: str = "peers",
                      vmem_budget_bytes: int = FUSED_VMEM_BUDGET,
                      on_refusal: str = "fallback"):
    """Build the round-16 tick-resident window: ``window(params,
    state)`` advances ``ticks_fused`` ticks in ONE pallas_call with a
    sequential ``(ticks,)`` grid, the whole per-shard carry resident
    in VMEM across grid steps (ops/pallas/receive.py
    make_fused_gossip_update).  Returns ``(state, delivered)`` with
    ``delivered`` u32 [T, W, N] — row t is tick ``state.tick + t``'s
    delivered words — or ``(state, delivered, frames)`` with
    ``telemetry`` (frames stacked [T, ...] like the scanned runners').

    Dispatch is by ``kernel_ticks_fused_capability``: where residency
    is impossible (scored carry, delays, a halo past the shard ring,
    carry past the VMEM budget — every refusal named and
    byte-reported) the window runs as a ``lax.scan`` of the ordinary
    step over the same T ticks, bit-identical by definition; pass
    ``on_refusal="raise"`` to surface the refusal instead.  On the
    resident path the trajectory is bit-identical to T per-tick steps
    on BOTH existing paths (pinned by tests/test_fused_kernel.py):
    the in-kernel tick body transcribes the unscored combined step op
    for op and the lane-hash draws are seeded per tick exactly as the
    step seeds them.  With ``shard_mesh`` (round 17) the window
    dispatches ``sharded_fused_gossip_update``: one resident pallas
    invocation PER SHARD whose in-kernel remote DMAs carry the
    ring-halo boundary words between grid ticks — residency and
    multi-chip sharding compose, still bit-identical (pinned at
    D in {2, 4} on the CPU virtual mesh).  Compose with checkpointing
    by aligning segment boundaries: ``ckpt run`` refuses
    ``every % ticks_fused != 0`` by name."""
    sc = score_cfg
    tel = telemetry
    T = int(ticks_fused)
    if T < 1:
        raise ValueError(_plan.msg_fused_window(T))
    shard_D = (int(shard_mesh.shape[shard_axis])
               if shard_mesh is not None else 1)
    step = make_gossip_step(cfg, sc, receive_block=receive_block,
                            receive_interpret=receive_interpret,
                            shard_mesh=shard_mesh,
                            shard_axis=shard_axis, telemetry=tel)
    step_gates_fp = gates_fingerprint(cfg, sc)
    C = cfg.n_candidates
    offsets = tuple(int(o) for o in cfg.offsets)
    cinv = cfg.cinv
    hg = cfg.history_gossip
    ALL = jnp.uint32((1 << C) - 1)
    Z = jnp.uint32(0)

    def fallback_window(params, state):
        def body(s, _):
            out = step(params, s)
            return out[0], out[1:]
        state, ys = jax.lax.scan(body, state, None, length=T)
        return (state,) + tuple(ys)

    def fused_window(params, state):
        from ..ops.pallas.receive import (
            TEL_PAYLOAD, TEL_IHAVE_IDS, TEL_IWANT_SERVED, TEL_RECV,
            TEL_IWANT_REQ, TEL_IHAVE_RPCS, TEL_IWANT_RPCS,
            TEL_NEW_IDS, TEL_ROWS, make_fused_gossip_update)

        n = params.subscribed.shape[0]
        n_true = params.n_true
        W = state.have.shape[0]
        tick0 = state.tick
        salt = jax.random.key_data(state.key)[-1]
        if state.gates is not None and len(state.gates) != 2:
            raise ValueError(
                f"state carries {len(state.gates)} gate words but "
                "this step's config expects 2 — the state was built "
                "for a different score config; rebuild it or "
                "refresh_gates with the matching config")
        if (state.gates_fp is not None
                and state.gates_fp != step_gates_fp):
            raise ValueError(
                "state's carried gates were emitted under a different "
                "(cfg, score_cfg) than this step's — refresh_gates "
                "with the new config before stepping")
        sub_all = jnp.where(params.subscribed, ALL, Z)
        tick_l = [tick0 + t for t in range(T)]
        seeds = jnp.stack([
            jnp.stack([lane_seed(tk, 4, salt), lane_seed(tk, 2, salt),
                       lane_seed(tk, 3, salt),
                       lane_seed(tk + 1, 1, salt)])
            for tk in tick_l])
        due = jnp.stack([pack_bits(params.publish_tick == tk)
                         for tk in tick_l])
        fp = params.faults
        with_f = fp is not None
        cold = with_f and fp.cold_restart
        lat_b = (tel.latency_buckets
                 if tel is not None and tel.latency_hist else 0)
        with_t = (tel is not None
                  and (tel.counters or lat_b > 0 or tel.mesh
                       or tel.degree_hist))
        alive_rows = sok_rows = cal_rows = rej_rows = None
        alive_u_l, link_u_l = [], []
        if with_f:
            n_tr = fp.down_start.shape[0]

            def fpad(a, fill):
                if a is None or n_tr == n:
                    return a
                return jnp.concatenate(
                    [a, jnp.full((n - n_tr,), fill, dtype=a.dtype)])

            a_l, s_l, c_l, r_l = [], [], [], []
            for tk in tick_l:
                f_alive_u = _faults.alive_mask(fp, tk)
                f_link_u = _faults.link_ok_bits(fp, offsets, cinv, tk,
                                                n_true)
                f_cand_u = _faults.cand_alive_bits(f_alive_u, offsets)
                alive_u_l.append(f_alive_u)
                link_u_l.append(f_link_u)
                f_alive = fpad(f_alive_u, True)
                f_alive_w = _faults.alive_word(f_alive)
                f_alive_all = jnp.where(f_alive, ALL, Z)
                f_link = fpad(f_link_u, ALL)
                f_send_ok = (f_alive_all if f_link is None
                             else f_alive_all & f_link)
                a_l.append(f_alive_w)
                s_l.append(f_send_ok)
                c_l.append(fpad(f_cand_u, ALL))
                if cold:
                    r_l.append(_faults.alive_word(
                        fpad(_faults.rejoined_mask(fp, tk), False)))
            alive_rows = jnp.stack(a_l)
            sok_rows = jnp.stack(s_l)
            cal_rows = jnp.stack(c_l)
            if cold:
                rej_rows = jnp.stack(r_l)
        if shard_mesh is not None:
            from ..ops.pallas.receive import sharded_fused_gossip_update
            krn = sharded_fused_gossip_update(
                cfg, n_true, W, hg, T, mesh=shard_mesh,
                axis_name=shard_axis, interpret=receive_interpret,
                with_faults=with_f, cold_restart=cold,
                with_telemetry=with_t, tel_lat_buckets=lat_b)
        else:
            krn = make_fused_gossip_update(
                cfg, n_true, W, hg, T, interpret=receive_interpret,
                stream_n=n_true, with_faults=with_f, cold_restart=cold,
                with_telemetry=with_t, tel_lat_buckets=lat_b)
        args = [jnp.asarray(tick0, jnp.int32).reshape(1), seeds, due,
                jnp.zeros((1,), jnp.uint32)]
        if with_t and lat_b:
            args.append(jnp.stack([_telemetry.latency_bucket_masks(
                params.publish_tick, tk, lat_b, W)
                for tk in tick_l]))
        args += [sub_all, params.cand_sub_bits, params.origin_words]
        if with_t and lat_b:
            args.append(params.deliver_words)
        args += [state.have, state.recent.reshape(hg * W, n),
                 state.mesh, state.fanout, state.last_pub,
                 state.backoff, state.gates[0], state.gates[1]]
        if with_f:
            args += [alive_rows, sok_rows, cal_rows]
        if cold:
            args += [rej_rows]
        outs = krn(*args)
        (have_f, rec_f, mesh_f, fan_f, lp_f, bo_f, tgt_f, bog_f,
         acq) = outs[:9]
        mesh_rows = tel_rows = None
        if with_t:
            mesh_rows, tel_rows = outs[9], outs[10]
        delivered = acq & params.deliver_words[None]
        ft = state.first_tick
        for t in range(T):
            ft = update_first_tick(ft, delivered[t], tick_l[t])
        new_state = state.replace(
            mesh=mesh_f, fanout=fan_f, last_pub=lp_f, backoff=bo_f,
            have=have_f, recent=rec_f.reshape(hg, W, n),
            first_tick=ft, tick=tick0 + T, gates=(tgt_f, bog_f))
        if tel is None:
            return new_state, delivered

        # -- per-tick frame assembly (resident path): the counter /
        # latency tallies come back as the kernel's per-tick emission
        # rows; graft/prune sends ride the two extra in-kernel rows
        # (the per-tick epilogue that counted them is fused away);
        # the mesh gauges reduce the emitted per-tick mesh rows; the
        # faults group recomputes the tick's mask words here — every
        # value equals the scanned step's frame bit for bit.
        ws = _telemetry.wire_sizes(tel)
        frames = []
        for t in range(T):
            kw_f = {}
            if tel.counters:
                sums = tel_rows[t].sum(axis=1)
                graft_cnt = sums[TEL_ROWS + lat_b]
                prune_cnt = sums[TEL_ROWS + lat_b + 1]
                kw_f.update(
                    payload_sent=sums[TEL_PAYLOAD],
                    ihave_rpcs=sums[TEL_IHAVE_RPCS],
                    ihave_ids=sums[TEL_IHAVE_IDS],
                    iwant_rpcs=sums[TEL_IWANT_RPCS],
                    iwant_ids_requested=sums[TEL_IWANT_REQ],
                    iwant_ids_served=sums[TEL_IWANT_SERVED],
                    graft_sends=graft_cnt, prune_sends=prune_cnt,
                    dup_suppressed=sums[TEL_RECV]
                    - sums[TEL_NEW_IDS])
                if tel.wire:
                    f32c = lambda x: x.astype(jnp.float32)  # noqa: E731
                    kw_f["bytes_payload"] = (
                        f32c(sums[TEL_PAYLOAD]
                             + sums[TEL_IWANT_SERVED])
                        * float(ws.payload_frame))
                    kw_f["bytes_control"] = (
                        f32c(sums[TEL_IHAVE_RPCS])
                        * float(ws.ihave_base)
                        + f32c(sums[TEL_IHAVE_IDS])
                        * float(ws.ihave_per_id)
                        + f32c(sums[TEL_IWANT_RPCS])
                        * float(ws.iwant_base)
                        + f32c(sums[TEL_IWANT_REQ])
                        * float(ws.iwant_per_id)
                        + f32c(graft_cnt) * float(ws.graft_frame)
                        + f32c(prune_cnt) * float(ws.prune_frame))
            if tel.mesh or tel.degree_hist:
                deg_t = popcount32(mesh_rows[t][:n_true])
                if tel.mesh:
                    mn_d, mean_d, mx_d = _telemetry.degree_stats(
                        deg_t, params.subscribed[:n_true])
                    kw_f.update(mesh_deg_min=mn_d,
                                mesh_deg_mean=mean_d,
                                mesh_deg_max=mx_d)
                if tel.degree_hist:
                    kw_f["mesh_deg_hist"] = \
                        _telemetry.degree_histogram(
                            deg_t, params.subscribed[:n_true],
                            tel.degree_buckets)
            if tel.latency_hist:
                kw_f["latency_hist"] = tel_rows[
                    t, TEL_ROWS:TEL_ROWS + lat_b].sum(
                        axis=1, dtype=jnp.int32)
            if tel.faults and with_f:
                kw_f["down_peers"] = (~alive_u_l[t]).sum(
                    dtype=jnp.int32)
                if link_u_l[t] is not None:
                    kw_f["dropped_edge_ticks"] = (
                        popcount32(~link_u_l[t] & ALL).sum(
                            dtype=jnp.int32)
                        // (1 if fp.directed_drops else 2))
            frames.append(_telemetry.make_frame(**kw_f))
        frames_st = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *frames)
        return new_state, delivered, frames_st

    def window(params, state):
        reason = kernel_ticks_fused_capability(
            cfg, sc, params, state, T,
            vmem_budget_bytes=vmem_budget_bytes,
            sharded=shard_mesh is not None, devices=shard_D)
        if reason is not None:
            if on_refusal == "raise":
                raise ValueError(reason)
            return fallback_window(params, state)
        return fused_window(params, state)

    window.ticks_fused = T
    window.capability = lambda params, state: \
        kernel_ticks_fused_capability(
            cfg, sc, params, state, T,
            vmem_budget_bytes=vmem_budget_bytes,
            sharded=shard_mesh is not None, devices=shard_D)
    return window


# --------------------------------------------------------------------------
# Runners / metrics (mirror models/floodsub.py)
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run(params: GossipParams, state: GossipState, n_ticks: int,
               step) -> GossipState:
    # jit (with step static) is load-bearing: a bare lax.scan call misses
    # the C++ dispatch fast path and costs ~4 ms/call of host overhead at
    # 1M peers — as much as the step itself.  The state carry is DONATED:
    # the scan writes the new carry into the input's buffers instead of
    # holding two full copies of the (up to ~GB-scale) state live across
    # the call.  Callers that still need the input state afterwards pass
    # tree_copy(state) (models/_batch.py).
    def body(s, _):
        return step(params, s)[0], None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def gossip_run_curve(params: GossipParams, state: GossipState, n_ticks: int,
                     step, n_msgs: int):
    """Run n_ticks collecting per-tick delivered counts [n_ticks, M].

    The state carry is donated (see gossip_run)."""
    def body(s, _):
        s2, delivered = step(params, s)
        return s2, count_bits_per_position(delivered, n_msgs)
    state, counts = jax.lax.scan(body, state, None, length=n_ticks)
    return state, counts


def _check_fused_horizon(n_ticks: int, ticks_fused: int) -> int:
    if n_ticks % ticks_fused != 0:
        raise ValueError(_plan.msg_fused_horizon(n_ticks, ticks_fused))
    return n_ticks // ticks_fused


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_fused(params: GossipParams, state: GossipState,
                     n_ticks: int, window) -> GossipState:
    """gossip_run over the tick-resident window (make_fused_window):
    the horizon chunks into ``n_ticks / window.ticks_fused`` fused
    windows scanned back to back — ONE pallas dispatch per window
    instead of per tick.  The final state is bit-identical to
    ``gossip_run`` with the per-tick step (pinned); a horizon the
    window does not divide raises by name.  State carry donated as in
    every runner."""
    n_win = _check_fused_horizon(n_ticks, window.ticks_fused)

    def body(s, _):
        return window(params, s)[0], None
    state, _ = jax.lax.scan(body, state, None, length=n_win)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def gossip_run_curve_fused(params: GossipParams, state: GossipState,
                           n_ticks: int, window, n_msgs: int):
    """gossip_run_curve over fused windows: per-tick delivered counts
    [n_ticks, M], rows bit-identical to the per-tick runner's."""
    n_win = _check_fused_horizon(n_ticks, window.ticks_fused)

    def body(s, _):
        s2, delivered = window(params, s)[:2]
        # delivered is [Tw, W, N]: one count row per fused tick
        return s2, jnp.stack([
            count_bits_per_position(delivered[t], n_msgs)
            for t in range(window.ticks_fused)])
    state, counts = jax.lax.scan(body, state, None, length=n_win)
    return state, counts.reshape(n_ticks, n_msgs)


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_frames_fused(params: GossipParams, state: GossipState,
                            n_ticks: int, window):
    """Telemetry runner over fused windows: returns ``(state,
    frames)`` with every TelemetryFrame leaf stacked [n_ticks, ...] —
    the same layout (and bit-identical values) as scanning the
    telemetry step."""
    n_win = _check_fused_horizon(n_ticks, window.ticks_fused)

    def body(s, _):
        s2, _delivered, frames = window(params, s)
        return s2, frames
    state, frames = jax.lax.scan(body, state, None, length=n_win)
    # [n_win, Tw, ...] -> [n_ticks, ...] per leaf
    return state, jax.tree_util.tree_map(
        lambda x: x.reshape((n_ticks,) + x.shape[2:]), frames)


# --------------------------------------------------------------------------
# Batched replica execution: B independent sims, one device program
# --------------------------------------------------------------------------


def stack_sims(cfg: GossipSimConfig, specs, **common):
    """Build B replicas of ONE static config and stack them for the
    batched runners: ``specs`` is a list of make_gossip_sim keyword
    dicts (subs, msg_topic, msg_origin, msg_publish_tick, seed, ...);
    ``common`` supplies kwargs shared by every replica.  Returns
    (params_B, state_B) with a leading replica axis on every leaf.

    All replicas share ``cfg`` (and any score_cfg) because the step
    bakes the circulant offsets in as compile-time constants — replicas
    may vary anything that lives in arrays: seed, publishers, message
    tables, subscriptions, sybil flags, fault schedules, ...  A spec
    that disagrees on STATIC config (score_cfg, track_first_tick,
    pad_to_block, px_candidates) raises here, naming the field, rather
    than failing later with an opaque vmap shape error.
    """
    static_keys = ("score_cfg", "track_first_tick", "pad_to_block",
                   "px_candidates")
    merged = [{**common, **spec} for spec in specs]
    for key in static_keys:
        vals = [m.get(key) for m in merged]
        for i, v in enumerate(vals[1:], start=1):
            if v != vals[0]:
                raise ValueError(
                    f"stack_sims: replica {i} spec disagrees with "
                    f"replica 0 on static config {key!r} "
                    f"({v!r} vs {vals[0]!r}) — all replicas of a batch "
                    "share one compiled step, so static config must "
                    "match (vary arrays instead)")
    builds = [make_gossip_sim(cfg, **m) for m in merged]
    return (stack_trees([b[0] for b in builds]),
            stack_trees([b[1] for b in builds]))


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_batch(params: GossipParams, state: GossipState,
                     n_ticks: int, step) -> GossipState:
    """Advance B stacked replicas (stack_sims / stack_trees) n_ticks in
    ONE scan of the vmapped step: one dispatch and one donated resident
    carry instead of B.  Per replica the trajectory is bit-identical to
    the sequential gossip_run (vmap adds no arithmetic; pinned by
    tests/test_gossipsub_sim.py::test_batch_matches_sequential)."""
    vstep = jax.vmap(step)

    def body(s, _):
        return vstep(params, s)[0], None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def gossip_run_curve_batch(params: GossipParams, state: GossipState,
                           n_ticks: int, step, n_msgs: int):
    """gossip_run_curve over B stacked replicas: returns
    (state_B, counts [n_ticks, B, M])."""
    vstep = jax.vmap(step)

    def body(s, _):
        s2, delivered = vstep(params, s)
        return s2, jax.vmap(
            lambda d: count_bits_per_position(d, n_msgs))(delivered)
    state, counts = jax.lax.scan(body, state, None, length=n_ticks)
    return state, counts


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_knob_batch(params: GossipParams, state: GossipState,
                          n_ticks: int, step, honest=None):
    """The sweep engine's device side (round 12): advance B stacked
    replicas — each carrying its OWN SimKnobs protocol point, fault
    tables, attack formation arrays, seed, and message schedule under
    ONE static config — ``n_ticks`` in ONE scan of the vmapped step,
    then reduce every replica's final per-message reach from the
    possession words, honest-masked when ``honest`` (bool [B, N]) is
    given.  B *different* scenarios, one compiled executable: no
    per-replica host round-trips, no recompiles across the batch (all
    heterogeneity is traced operands — stack the per-replica
    (params, state) with ``stack_trees``).  Returns
    ``(state_B, reach [B, M])``; the state carry is donated like every
    runner (models/_batch.py tree_copy for reuse).  With
    invariant-armed states the per-replica violation masks come back
    in ``state_B.inv_viol`` — every scenario doubles as a property
    test.  Per replica the trajectory is bit-identical to the
    sequential gossip_run (vmap adds no arithmetic; pinned by
    tests/test_knobs.py).

    The round-11 attack × defense tournament (models/tournament.py)
    runs on this dispatch — ``gossip_run_tournament`` is this
    function."""
    vstep = jax.vmap(step)

    def body(s, _):
        return vstep(params, s)[0], None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    if honest is None:
        reach = jax.vmap(
            lambda p, s: reach_counts_from_have(p, s))(params, state)
    else:
        reach = jax.vmap(reach_counts_from_have)(params, state,
                                                 honest)
    return state, reach


#: the round-11 name: the tournament was the first knob-batched sweep;
#: round 12 generalized its runner to the whole scenario surface
gossip_run_tournament = gossip_run_knob_batch


def eclipse_takeover(state: GossipState, params: GossipParams,
                     cfg: GossipSimConfig) -> float:
    """Host-side eclipse metric: the fraction of the VICTIM set's
    occupied mesh slots held by eclipse attackers (0 = clean mesh,
    1 = fully eclipsed).  Stated over victims with nonzero degree;
    pad lanes excluded on padded states."""
    mesh = np.asarray(state.mesh)
    es = np.asarray(params.eclipse_sybil)
    ev = np.asarray(params.eclipse_victim)
    n = params.n_true if params.n_true is not None else mesh.shape[-1]
    mesh, es, ev = mesh[..., :n], es[..., :n], ev[..., :n]
    occ = np.zeros(mesh.shape, dtype=np.int64)
    deg = np.zeros(mesh.shape, dtype=np.int64)
    for c, o in enumerate(cfg.offsets):
        bit = ((mesh >> np.uint32(c)) & 1).astype(bool)
        deg += bit
        occ += bit & np.roll(es, -int(o), axis=-1)
    v_deg = deg[ev].sum()
    return float(occ[ev].sum() / max(v_deg, 1))


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_mesh_snapshots(params: GossipParams, state: GossipState,
                              n_ticks: int, step):
    """Advance n_ticks collecting the END-of-tick mesh word per tick:
    returns ``(state, snaps)`` where ``snaps["mesh"]`` is uint32
    [n_ticks, N] (plus ``"mesh_b"`` in paired mode).  Row k is the mesh
    AFTER tick ``start_tick + k`` — feed it (with the pre-run mesh as
    the baseline) to interop.export.mesh_trace_events, whose host-side
    diff emits the reference's GRAFT/PRUNE TraceEvents (trace.proto
    types 11/12).  Works with any step, telemetry-enabled or not."""
    def body(s, _):
        s2 = step(params, s)[0]
        snap = {"mesh": s2.mesh}
        if s2.mesh_b is not None:
            snap["mesh_b"] = s2.mesh_b
        return s2, snap
    return jax.lax.scan(body, state, None, length=n_ticks)


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_acq_snapshots(params: GossipParams, state: GossipState,
                             n_ticks: int, step):
    """Advance n_ticks collecting END-of-tick possession AND mesh
    words per tick: returns ``(state, snaps)`` where ``snaps["have"]``
    is uint32 [n_ticks, W, N] and ``snaps["mesh"]`` uint32
    [n_ticks, N] (plus ``"mesh_b"`` in paired mode).  The host-side
    event exporters diff these into reference-format TraceEvents:
    interop.export.reject_events (REJECT_MESSAGE from invalid-id
    acquisitions) and interop.export.duplicate_events (seen-cache
    DUPLICATE_MESSAGE from an eager-forward replay over the recorded
    meshes).  Collection cost is W+1 [N] words per tick — export
    runs, not benches."""
    def body(s, _):
        s2 = step(params, s)[0]
        snap = {"have": s2.have, "mesh": s2.mesh}
        if s2.mesh_b is not None:
            snap["mesh_b"] = s2.mesh_b
        return s2, snap
    return jax.lax.scan(body, state, None, length=n_ticks)


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def gossip_run_rpc_snapshots(params: GossipParams, state: GossipState,
                             n_ticks: int, step):
    """Advance n_ticks collecting the per-tick per-edge RPC probe dict
    (round 10): returns ``(state, snaps)`` where every probe leaf
    gains a leading [n_ticks] axis.  ``step`` must be built with
    ``make_gossip_step(..., rpc_probe=True)`` (either execution path;
    the probe dict is the step's LAST output either way) — feed the
    snaps to interop.export.rpc_events, which reconstructs the
    reference's SEND_RPC / RECV_RPC / DROP_RPC metadata streams
    host-side (fault-masked edges emitting DROP_RPC).  Collection cost
    is ~3W+6 [N] words per tick — export runs, not benches."""
    def body(s, _):
        out = step(params, s)
        return out[0], out[-1]
    return jax.lax.scan(body, state, None, length=n_ticks)


def first_tick_matrix(state: GossipState, m: int) -> jnp.ndarray:
    return first_tick_to_matrix(state.first_tick, m)


def reach_by_hops(params: GossipParams, state: GossipState,
                  max_hops: int,
                  mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """[M, max_hops] cumulative deliveries by hop (publish-relative) —
    the reachability-vs-hops curve of the BASELINE.md contract, directly
    comparable with interop.reach_by_hops_from_trace.

    Optional [N] bool ``mask`` restricts the count to a peer subset
    (e.g. honest peers only, matching the population semantics of the
    reference's spam tests where attackers are out-of-band mocks and
    reach is stated over the honest nodes —
    gossipsub_spam_test.go:563-709)."""
    m = params.publish_tick.shape[0]
    ft = first_tick_to_matrix(state.first_tick, m)          # [N, M] abs
    rel = jnp.where(ft >= 0, ft - params.publish_tick[None, :],
                    jnp.int32(-1))
    if mask is not None:
        rel = jnp.where(jnp.asarray(mask)[:, None], rel, jnp.int32(-1))
    hops = jnp.arange(max_hops, dtype=jnp.int32)
    per_hop = (rel[None, :, :] == hops[:, None, None]).sum(
        axis=1, dtype=jnp.int32)
    return jnp.cumsum(per_hop, axis=0).T


def reach_counts(params: GossipParams, state: GossipState) -> jnp.ndarray:
    return reach_counts_from_first_tick(state.first_tick,
                                        params.publish_tick.shape[0])


def reach_counts_from_have(params: GossipParams, state: GossipState,
                           mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Per-message reached-peer counts from the packed possession words.

    Works with ``track_first_tick=False`` — the bench path, where the
    timed loop must not carry per-delivery record traffic (the final
    reach is the correctness gate, hop curves are not needed).  Optional
    [N] bool ``mask`` restricts the count (e.g. honest peers only)."""
    m = params.publish_tick.shape[0]
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    bits = (state.have[:, None, :] >> shifts[None, :, None]) & jnp.uint32(1)
    if mask is not None:
        bits = bits * jnp.asarray(mask).astype(jnp.uint32)[None, None, :]
    return bits.astype(jnp.int32).sum(axis=2).reshape(-1)[:m]


def mesh_degrees(state: GossipState) -> jnp.ndarray:
    return popcount32(state.mesh)


def iwant_serve_level(state: GossipState, cfg: GossipSimConfig,
                      n_true: int | None = None) -> jnp.ndarray:
    """Per-SERVER outstanding gossip-retransmission load [n].

    ``iwant_serves[c, p]`` is stored at the REQUESTER p (receiver-side,
    so the hot path reuses the provenance popcounts); the load it
    represents lands on p's candidate-c partner at p + offset_c.  The
    read-time transfer rolls each row back to the serving peer.  With
    the cutoff active a victim's load is bounded by
    C * gossip_retransmission * window_ids regardless of flood pressure
    (TestGossipsubAttackSpamIWANT's assertion,
    gossipsub_spam_test.go:24).

    For pallas-padded states pass ``n_true`` (GossipParams.n_true): the
    topology wraps at the TRUE peer count, not the padded length.  Pad-
    lane LEDGER rows can carry nonzero garbage (the kernel's edge views
    read wrapped data through them even though pad peers never own
    state) — they are excluded here by slicing, and must be excluded by
    any other consumer of ``state.iwant_serves`` on a padded state."""
    s32 = state.iwant_serves.astype(jnp.int32)
    n = s32.shape[1] if n_true is None else n_true
    level = jnp.zeros((n,), dtype=jnp.int32)
    for c, off in enumerate(cfg.offsets):
        # requester row c at peer p burdens the server at p + off_c:
        # roll(x, off)[p + off] = x[p]
        level = level + jnp.roll(s32[c, :n], int(off))
    return level


def mesh_symmetry_fraction(state: GossipState,
                           cfg: GossipSimConfig) -> jnp.ndarray:
    """Fraction of mesh edges whose partner also has the edge (after the
    GRAFT/PRUNE handshake settles this should approach 1)."""
    partner = transfer_bits(state.mesh, cfg)
    agree = popcount32(state.mesh & partner).sum()
    total = popcount32(state.mesh).sum()
    return agree / jnp.maximum(total, 1)
