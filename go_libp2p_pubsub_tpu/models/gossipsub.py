"""GossipSub simulator: mesh overlay + lazy gossip, every peer at once.

The vectorized counterpart of the protocol core's GossipSubRouter
(core/gossipsub.py; reference /root/reference/gossipsub.go).  One jitted
``step`` advances one heartbeat for ALL simulated peers: mesh forwarding,
IHAVE/IWANT gossip repair, then the heartbeat maintenance pass
(graft-to-D / prune-to-D, backoff, fanout TTL — gossipsub.go:1299-1552).

TPU-first representation (see PERF_NOTES.md):

- **Topology = per-topic random circulants.**  Peer p belongs to topic
  ``p mod T``; the candidate-neighbor set of every peer is a static list of
  C ring offsets, all multiples of T and closed under negation.  Candidates
  model what discovery + peer exchange give a deployed node: the topic
  peers it *could* connect to (discovery.go:108-173, PX gossipsub.go:856).
- **Mesh/fanout/gossip-targets = bool masks [N, C]** over those candidate
  columns.  GRAFT/PRUNE flip mask bits; degree bounds (D/Dlo/Dhi,
  gossipsub.go:33-40) make C a small compile-time constant.
- **Edge duality is a column permutation + roll.**  The link (p, p+o_c)
  seen from the partner is column ``cinv[c]`` where ``o_cinv = -o_c``, so
  sending per-edge data to the partner — GRAFT/PRUNE announcements,
  message words — is ``roll(x[:, c], o_c)`` landing in column cinv[c].
  The whole heartbeat is rolls, masks, popcounts, and two tiny per-row
  argsorts: **no gathers** (XLA gather is ~1000x slower than roll on this
  topology; PERF_NOTES.md).
- **Messages are bit positions** in uint32 words, as in models/floodsub.py.
  The mcache (mcache.go) becomes a ring of recently-acquired words: slot 0
  = newest heartbeat window; IHAVE advertises the OR of the newest
  HistoryGossip slots (mcache.go:82, GetGossipIDs).

Timing model: one tick = one heartbeat = one network hop.  Reachability is
measured in hops (publish-tick-relative), which is exactly the
reachability-vs-hops contract from BASELINE.md and independent of the
wall-clock heartbeat/RTT ratio.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops.graph import (
    WORD_BITS,
    count_bits_per_position,
    make_circulant_offsets,
    pack_bits,
    select_k_by_priority,
    select_k_per_row,
)
from ._delivery import (
    reach_counts_from_first_tick,
    first_tick_to_matrix,
    update_first_tick,
)


# --------------------------------------------------------------------------
# Static configuration (baked into the compiled step as constants)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class GossipSimConfig:
    """Static simulator config.  Protocol defaults mirror GossipSubParams
    (core/gossipsub.py:61; reference gossipsub.go:31-59)."""

    offsets: tuple[int, ...]       # C candidate ring offsets, ± paired
    n_topics: int = 1
    d: int = 6                     # GossipSubD
    d_lo: int = 5                  # GossipSubDlo
    d_hi: int = 12                 # GossipSubDhi
    d_score: int = 4               # GossipSubDscore (v1.1 prune retention)
    d_out: int = 2                 # GossipSubDout (outbound quota)
    d_lazy: int = 6                # GossipSubDlazy
    gossip_factor: float = 0.25    # GossipSubGossipFactor
    history_gossip: int = 3        # GossipSubHistoryGossip (IHAVE window)
    backoff_ticks: int = 60        # GossipSubPruneBackoff / heartbeat
    fanout_ttl_ticks: int = 60     # GossipSubFanoutTTL / heartbeat

    def __post_init__(self):
        offs = np.asarray(self.offsets, dtype=np.int64)
        if len(offs) == 0 or len(set(offs.tolist())) != len(offs):
            raise ValueError("offsets must be distinct and non-empty")
        if not all((-o) in set(offs.tolist()) for o in offs.tolist()):
            raise ValueError("offsets must be closed under negation")
        if any(o % self.n_topics for o in offs.tolist()):
            raise ValueError("offsets must be multiples of n_topics")
        if not (self.d_lo <= self.d <= self.d_hi):
            raise ValueError("need Dlo <= D <= Dhi (gossipsub.go:33-35)")
        if self.d_score > self.d:
            raise ValueError("need Dscore <= D")
        if self.d_out >= self.d_lo or self.d_out > self.d // 2:
            raise ValueError(
                "need Dout < Dlo and Dout <= D/2 (gossipsub.go:266-272)")
        if self.d_hi >= len(offs):
            raise ValueError("need C > Dhi candidate columns")

    @property
    def n_candidates(self) -> int:
        return len(self.offsets)

    @property
    def cinv(self) -> tuple[int, ...]:
        """cinv[c] = column of the negated offset (the partner's view of
        edge column c)."""
        idx = {o: i for i, o in enumerate(self.offsets)}
        return tuple(idx[-o] for o in self.offsets)


def make_gossip_offsets(n_topics: int, n_candidates: int, n_peers: int,
                        seed: int = 0) -> tuple[int, ...]:
    """Random ± paired circulant offsets ≡ 0 (mod n_topics): each residue
    class (= topic) forms an independent random circulant candidate graph
    (expander — same locally-tree-like spread as the reference test
    harness's random topologies, floodsub_test.go:65-81)."""
    offs = make_circulant_offsets(n_topics, n_candidates, n_peers,
                                  seed=seed)
    return tuple(int(o) for o in offs)


@dataclass(frozen=True)
class ScoreSimConfig:
    """Static v1.1 hardening config: the peer-score formula (P1..P7,
    score.go:256-333), thresholds (score_params.go:12-32), and the sybil
    behavior toggles for adversarial runs (gossipsub_spam_test.go).

    Decays are per-tick factors (one tick = one heartbeat); the reference's
    ScoreParameterDecay math (score_params.go:277-287) converts wall-clock
    decays to this form.  Weights follow the reference's sign invariants
    (score_params.go:34-268): P1/P2/P5 >= 0, P3/P3b/P4/P6/P7 <= 0.
    """

    topic_weight: float = 1.0
    # P1: time in mesh (capped ramp)
    time_in_mesh_weight: float = 0.1
    time_in_mesh_quantum: int = 1           # ticks per unit
    time_in_mesh_cap: float = 10.0
    # P2: first message deliveries (decaying, capped counter)
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.9
    first_message_deliveries_cap: float = 50.0
    # P3: mesh message delivery deficit (squared, below threshold, only
    # after the edge has been in the mesh for the activation window).
    # Weight defaults to 0 (disabled): like the reference — which ships
    # no default score params at all — P3's threshold must be calibrated
    # to the topic's expected message rate, or quiet meshes churn.
    mesh_message_deliveries_weight: float = 0.0
    mesh_message_deliveries_decay: float = 0.9
    mesh_message_deliveries_cap: float = 20.0
    mesh_message_deliveries_threshold: float = 1.0
    mesh_message_deliveries_activation: int = 5   # ticks
    # P3b: sticky failure penalty applied at prune time
    mesh_failure_penalty_weight: float = 0.0
    mesh_failure_penalty_decay: float = 0.9
    # P4: invalid message deliveries (squared)
    invalid_message_deliveries_weight: float = -10.0
    invalid_message_deliveries_decay: float = 0.95
    # P5: application-specific (per-peer value supplied in params)
    app_specific_weight: float = 1.0
    # P6: IP colocation (squared surplus over threshold)
    ip_colocation_factor_weight: float = -5.0
    ip_colocation_factor_threshold: float = 1.0
    # P7: behavioural penalty (squared surplus; broken IWANT promises +
    # GRAFT-during-backoff violations, gossipsub.go:747-765,1566-1571)
    behaviour_penalty_weight: float = -10.0
    behaviour_penalty_decay: float = 0.9
    behaviour_penalty_threshold: float = 0.0
    decay_to_zero: float = 0.01
    # thresholds (PeerScoreThresholds, score_params.go:12-32)
    gossip_threshold: float = -10.0
    publish_threshold: float = -50.0
    graylist_threshold: float = -80.0
    opportunistic_graft_threshold: float = 1.0
    opportunistic_graft_ticks: int = 60
    opportunistic_graft_peers: int = 2
    # router options
    flood_publish: bool = False             # WithFloodPublish
    # sybil behavior toggles (peers flagged sybil in params)
    sybil_ihave_spam: bool = False          # broken-promise IWANT flood
    sybil_graft_flood: bool = False         # re-GRAFT while backed off

    def validate(self) -> None:
        """The reference's sign/range invariants are free tests
        (score_params.go:34-268)."""
        if self.topic_weight < 0:
            raise ValueError("topic_weight must be >= 0")
        for name in ("time_in_mesh_weight", "first_message_deliveries_weight",
                     "app_specific_weight"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        for name in ("mesh_message_deliveries_weight",
                     "mesh_failure_penalty_weight",
                     "invalid_message_deliveries_weight",
                     "ip_colocation_factor_weight",
                     "behaviour_penalty_weight"):
            if getattr(self, name) > 0:
                raise ValueError(f"{name} must be <= 0")
        for name in ("first_message_deliveries_decay",
                     "mesh_message_deliveries_decay",
                     "mesh_failure_penalty_decay",
                     "invalid_message_deliveries_decay",
                     "behaviour_penalty_decay"):
            d = getattr(self, name)
            if not (0 < d < 1):
                raise ValueError(f"{name} must be in (0, 1)")
        if not (self.graylist_threshold <= self.publish_threshold
                <= self.gossip_threshold <= 0):
            raise ValueError(
                "need graylist <= publish <= gossip threshold <= 0")


# --------------------------------------------------------------------------
# Pytrees
# --------------------------------------------------------------------------


@struct.dataclass
class GossipParams:
    """Per-simulation device arrays (dynamic operands of the jitted step).

    The v1.1 fields (None when scoring is off) carry per-CANDIDATE views of
    static per-peer attributes: column c of row p describes peer p+o_c.
    """

    subscribed: jnp.ndarray      # bool [N]: has a local subscription
    cand_subscribed: jnp.ndarray # bool [N, C]: candidate q=p+o_c subscribed
    origin_words: jnp.ndarray    # uint32 [N, W]: bit m set at origin[m]
    deliver_words: jnp.ndarray   # uint32 [N, W]: msg m counts as delivery
    publish_tick: jnp.ndarray    # int32 [M]
    invalid_words: jnp.ndarray | None = None  # uint32 [W]: msg fails validation
    cand_app_score: jnp.ndarray | None = None # f32 [N, C]: P5 of candidate
    cand_colo_excess: jnp.ndarray | None = None  # f32 [N, C]: P6 surplus
    cand_sybil: jnp.ndarray | None = None     # bool [N, C]: candidate is sybil
    sybil: jnp.ndarray | None = None          # bool [N]


@struct.dataclass
class ScoreState:
    """Per-edge v1.1 reputation counters: row p, column c = p's view of
    candidate p+o_c (the score engine's per-(peer, topic) stats,
    score.go:95-118, densified on the candidate axis)."""

    time_in_mesh: jnp.ndarray        # f32 [N, C] ticks since graft (P1)
    first_deliveries: jnp.ndarray    # f32 [N, C] decaying counter (P2)
    mesh_deliveries: jnp.ndarray     # f32 [N, C] decaying counter (P3)
    mesh_failure_penalty: jnp.ndarray  # f32 [N, C] sticky deficit² (P3b)
    invalid_deliveries: jnp.ndarray  # f32 [N, C] decaying counter (P4)
    behaviour_penalty: jnp.ndarray   # f32 [N, C] decaying counter (P7)


@struct.dataclass
class GossipState:
    mesh: jnp.ndarray        # bool [N, C]  my mesh membership per candidate
    fanout: jnp.ndarray      # bool [N, C]  publish-without-join targets
    last_pub: jnp.ndarray    # int32 [N]    last publish tick (fanout TTL)
    backoff: jnp.ndarray     # int32 [N, C] no re-GRAFT until this tick
    have: jnp.ndarray        # uint32 [N, W]
    recent: jnp.ndarray      # uint32 [N, Hg, W] newly-acquired ring (mcache)
    first_tick: jnp.ndarray  # int16 [N, W, 32] or None
    scores: ScoreState | None  # None when v1.1 scoring is disabled
    key: jax.Array           # PRNG key
    tick: jnp.ndarray        # int32 scalar


def make_gossip_sim(cfg: GossipSimConfig, subs: np.ndarray,
                    msg_topic: np.ndarray, msg_origin: np.ndarray,
                    msg_publish_tick: np.ndarray, seed: int = 0,
                    track_first_tick: bool = True,
                    score_cfg: ScoreSimConfig | None = None,
                    app_score: np.ndarray | None = None,
                    peer_ip: np.ndarray | None = None,
                    sybil: np.ndarray | None = None,
                    msg_invalid: np.ndarray | None = None):
    """Build (params, state).  subs: bool [N, T] — but each peer may only
    subscribe to its residue-class topic (circulant classes are closed, so
    cross-class subscriptions would never receive anything).

    With score_cfg, the v1.1 reputation layer is enabled:
    - app_score [N] f32: P5 application-specific score per peer
    - peer_ip [N] int: IP assignment; peers sharing an IP accrue the P6
      colocation penalty (sybils behind one address share fate,
      score.go:967-1007)
    - sybil [N] bool: peers running the configured attack behaviors
    - msg_invalid [M] bool: messages that fail validation (P4 + no
      forwarding, validation.go:274-351)
    """
    n, t = subs.shape
    if t != cfg.n_topics:
        raise ValueError("subs topic dim != cfg.n_topics")
    own_topic = np.arange(n) % cfg.n_topics
    cross = subs & ~(np.arange(t)[None, :] == own_topic[:, None])
    if cross.any():
        raise ValueError("peers may only subscribe to topic (p mod T)")
    subscribed = subs[np.arange(n), own_topic]

    m = len(msg_topic)
    if ((msg_origin % cfg.n_topics) != msg_topic).any():
        raise ValueError("msg origin must be in the topic's residue class")
    origin_bits = np.zeros((n, m), dtype=bool)
    origin_bits[msg_origin, np.arange(m)] = True
    deliver_bits = subscribed[:, None] & (own_topic[:, None]
                                          == msg_topic[None, :])

    def cand_view(per_peer):
        """Per-candidate view: out[p, c] = per_peer[p + o_c]."""
        return np.stack([np.roll(per_peer, -o) for o in cfg.offsets], axis=1)

    kw = {}
    if score_cfg is not None:
        score_cfg.validate()
        app = (np.zeros(n, dtype=np.float32) if app_score is None
               else np.asarray(app_score, dtype=np.float32))
        syb = (np.zeros(n, dtype=bool) if sybil is None
               else np.asarray(sybil, dtype=bool))
        if peer_ip is None:
            peer_ip = np.arange(n)  # everyone on their own address
        _, ip_idx = np.unique(np.asarray(peer_ip), return_inverse=True)
        colo_count = np.bincount(ip_idx)[ip_idx].astype(np.float32)
        colo_excess = np.maximum(
            0.0, colo_count - score_cfg.ip_colocation_factor_threshold)
        inv = (np.zeros(m, dtype=bool) if msg_invalid is None
               else np.asarray(msg_invalid, dtype=bool))
        kw = dict(
            invalid_words=pack_bits(jnp.asarray(inv)),
            cand_app_score=jnp.asarray(cand_view(app)),
            cand_colo_excess=jnp.asarray(cand_view(colo_excess)),
            cand_sybil=jnp.asarray(cand_view(syb)),
            sybil=jnp.asarray(syb),
        )

    params = GossipParams(
        subscribed=jnp.asarray(subscribed),
        cand_subscribed=jnp.asarray(cand_view(subscribed)),
        origin_words=pack_bits(jnp.asarray(origin_bits)),
        deliver_words=pack_bits(jnp.asarray(deliver_bits)),
        publish_tick=jnp.asarray(msg_publish_tick, dtype=jnp.int32),
        **kw,
    )
    w = params.origin_words.shape[1]
    c = cfg.n_candidates
    zc = lambda: jnp.zeros((n, c), dtype=jnp.float32)  # noqa: E731
    state = GossipState(
        mesh=jnp.zeros((n, c), dtype=bool),
        fanout=jnp.zeros((n, c), dtype=bool),
        last_pub=jnp.full((n,), -(10 ** 9), dtype=jnp.int32),
        backoff=jnp.zeros((n, c), dtype=jnp.int32),
        have=jnp.zeros((n, w), dtype=jnp.uint32),
        recent=jnp.zeros((n, cfg.history_gossip, w), dtype=jnp.uint32),
        first_tick=(jnp.full((n, w, WORD_BITS), -1, dtype=jnp.int16)
                    if track_first_tick else None),
        scores=(ScoreState(time_in_mesh=zc(), first_deliveries=zc(),
                           mesh_deliveries=zc(), mesh_failure_penalty=zc(),
                           invalid_deliveries=zc(), behaviour_penalty=zc())
                if score_cfg is not None else None),
        key=jax.random.PRNGKey(seed),
        tick=jnp.zeros((), dtype=jnp.int32),
    )
    return params, state


# --------------------------------------------------------------------------
# Edge transfer: per-edge data -> the partner's view of the same edge
# --------------------------------------------------------------------------


def edge_transfer(cols: list[jnp.ndarray], cfg: GossipSimConfig):
    """Given per-column arrays (each [N, ...], column c describing edge
    (p, p+o_c)), return the received per-column list: out[cinv[c]] =
    roll(cols[c], o_c) — what each peer's partner sent it on that edge."""
    out = [None] * cfg.n_candidates
    for c, off in enumerate(cfg.offsets):
        out[cfg.cinv[c]] = jnp.roll(cols[c], off, axis=0)
    return out


def transfer_mask(mask: jnp.ndarray, cfg: GossipSimConfig) -> jnp.ndarray:
    """edge_transfer for a bool [N, C] mask (column-stacked form)."""
    cols = edge_transfer([mask[:, c] for c in range(cfg.n_candidates)], cfg)
    return jnp.stack(cols, axis=1)


def masked_word_or(words: jnp.ndarray, mask: jnp.ndarray,
                   cfg: GossipSimConfig) -> jnp.ndarray:
    """OR of ``words`` sent along every masked edge: what each peer hears.

    words: uint32 [N, W] (sender payload); mask: bool [N, C] (sender's
    out-edges).  One roll per candidate column — the hot op.
    """
    out = jnp.zeros_like(words)
    for c, off in enumerate(cfg.offsets):
        sent = jnp.where(mask[:, c, None], words, jnp.uint32(0))
        out = out | jnp.roll(sent, off, axis=0)
    return out


# --------------------------------------------------------------------------
# The step
# --------------------------------------------------------------------------


def compute_scores(sc: ScoreSimConfig, params: GossipParams,
                   st: GossipState) -> jnp.ndarray:
    """The peer-score formula, densified: f32 [N, C] — row p's opinion of
    candidate p+o_c (score.go:256-333).  One topic per peer, so the
    per-topic sum collapses to the single topic's contribution."""
    s = st.scores
    p1 = jnp.minimum(s.time_in_mesh / sc.time_in_mesh_quantum,
                     sc.time_in_mesh_cap)
    p2 = s.first_deliveries                    # capped at increment time
    deficit = jnp.maximum(
        0.0, sc.mesh_message_deliveries_threshold - s.mesh_deliveries)
    active = s.time_in_mesh > sc.mesh_message_deliveries_activation
    p3 = jnp.where(st.mesh & active, deficit * deficit, 0.0)
    topic = (sc.time_in_mesh_weight * p1
             + sc.first_message_deliveries_weight * p2
             + sc.mesh_message_deliveries_weight * p3
             + sc.mesh_failure_penalty_weight * s.mesh_failure_penalty
             + sc.invalid_message_deliveries_weight
             * s.invalid_deliveries * s.invalid_deliveries)
    bp_excess = jnp.maximum(
        0.0, s.behaviour_penalty - sc.behaviour_penalty_threshold)
    return (sc.topic_weight * topic
            + sc.app_specific_weight * params.cand_app_score
            + sc.ip_colocation_factor_weight
            * params.cand_colo_excess * params.cand_colo_excess
            + sc.behaviour_penalty_weight * bp_excess * bp_excess)


def make_gossip_step(cfg: GossipSimConfig,
                     score_cfg: ScoreSimConfig | None = None):
    """Build the jittable (params, state) -> (state, delivered_words) core.

    Per tick:
      1. inject due publishes (Topic.Publish -> rt.Publish, topic.go:207)
      2. eager forward: newly-acquired words flow one hop along mesh ∪
         fanout edges (forwardMessage to mesh, gossipsub.go:989-999)
      3. lazy gossip: IHAVE of the recent window to Dlazy/gossip-factor
         random non-mesh candidates; receivers pull what they lack
         (emitGossip gossipsub.go:1656-1712 + handleIHave/IWant :610-711)
      4. heartbeat maintenance: graft to D when deg<Dlo, prune to D when
         deg>Dhi, GRAFT/PRUNE handshake with backoff, fanout TTL
         (heartbeat gossipsub.go:1299-1552)

    With score_cfg, the v1.1 hardening layer is woven through every phase:
    start-of-tick scores gate inbound RPCs (graylist), gossip exchange
    (gossip threshold), and publish flooding (publish threshold); delivery
    provenance per candidate column feeds the P2/P3/P4 counters; mesh
    maintenance prunes negative-score peers, keeps the Dscore best + Dout
    outbound on oversubscription (gossipsub.go:1376-1435), and
    opportunistically grafts when the mesh median sags
    (gossipsub.go:1467-1498); a RED gater drops payload from edges with
    bad goodput under invalid-traffic pressure (peer_gater.go:320-363).
    """
    C = cfg.n_candidates
    sc = score_cfg
    outbound_cols = jnp.asarray(
        np.array([o > 0 for o in cfg.offsets]))    # we dial positive offsets

    def step(params: GossipParams, state: GossipState):
        key, k_gossip, k_graft, k_prune, k_fanout, k_og, k_gater = \
            jax.random.split(state.key, 7)
        tick = state.tick
        sub = params.subscribed
        n = sub.shape[0]

        # -- 0. start-of-tick scores and the gates they drive -----------
        if sc is not None:
            score = compute_scores(sc, params, state)           # [N, C]
            # graylist: drop ALL inbound on edges below the graylist
            # threshold (AcceptFrom, gossipsub.go:584-586)
            edge_accept = score >= sc.graylist_threshold
            gossip_ok = score >= sc.gossip_threshold
            # RED gater: under invalid-traffic pressure, payload from an
            # edge is accepted with its goodput probability
            # (peer_gater.go:320-363; stats per edge, decayed with the
            # score counters — sybils behind one IP already share fate
            # via P6)
            s0 = state.scores
            inv_tot = s0.invalid_deliveries.sum(axis=1)
            del_tot = s0.first_deliveries.sum(axis=1)
            pressure = 16.0 * inv_tot / (1.0 + del_tot + 16.0 * inv_tot)
            gater_on = pressure > 0.33
            goodput = ((1.0 + s0.first_deliveries)
                       / (1.0 + s0.first_deliveries
                          + 16.0 * s0.invalid_deliveries))
            p_accept = jnp.where(gater_on[:, None], goodput, 1.0)
            gater_ok = jax.random.uniform(k_gater, (n, C)) < p_accept
            payload_ok = edge_accept & gater_ok
            valid_words = ~params.invalid_words[None, :]        # [1, W]
        else:
            score = None
            edge_accept = gossip_ok = payload_ok = None
            valid_words = None

        # -- 1. publish injection ---------------------------------------
        due = pack_bits(params.publish_tick == tick)            # [W]
        injected = params.origin_words & due[None, :] & ~state.have
        publishing = (injected != 0).any(axis=1)                # [N]

        # -- 1b. fanout build/maintenance (BEFORE forwarding: the
        # reference selects fanout peers on demand at publish time,
        # gossipsub.go:961-983; TTL expiry + refill per heartbeat
        # :1505-1542).  Fanout only ever carries the owner's own
        # publishes — unsubscribed peers accept nothing to relay.
        last_pub = jnp.where(publishing, tick, state.last_pub)
        alive = (~sub) & (tick - last_pub < cfg.fanout_ttl_ticks)
        fanout = state.fanout & alive[:, None]
        f_deg = fanout.sum(axis=1, dtype=jnp.int32)
        f_need = jnp.where(alive, cfg.d - f_deg, 0)
        f_elig = params.cand_subscribed & ~fanout
        if sc is not None:  # fanout requires score >= publish threshold
            f_elig = f_elig & (score >= sc.publish_threshold)
        fanout = fanout | select_k_per_row(f_elig, f_need, k_fanout)

        # -- 2. eager forward with per-edge provenance ------------------
        # What I acquired last tick + my fresh publishes go to my mesh /
        # fanout (forwardMessage, gossipsub.go:989-999).  Honest peers
        # never forward invalid messages (validation rejects them before
        # the router sees them, validation.go:274-351); sybils do.
        fresh = state.recent[:, 0] | injected
        if sc is not None:
            fresh = jnp.where(params.sybil[:, None], fresh,
                              fresh & valid_words)
        out_edges = state.mesh | fanout
        if sc is not None and sc.flood_publish:
            # own publishes additionally flood to every candidate above
            # the publish threshold (gossipsub.go:953-959)
            flood_edges = params.cand_subscribed & (
                score >= sc.publish_threshold)
        else:
            flood_edges = None

        have_start = state.have
        claimed = injected          # first-arrival provenance accumulator
        fd_add = [None] * C         # per-receiver-column popcounts
        md_new = [None] * C
        inv_add = [None] * C
        for c_send, off in enumerate(cfg.offsets):
            j = cfg.cinv[c_send]    # receiver-side column for this edge
            sent = jnp.where(out_edges[:, c_send, None], fresh,
                             jnp.uint32(0))
            if flood_edges is not None:
                sent = sent | jnp.where(flood_edges[:, c_send, None],
                                        injected, jnp.uint32(0))
            rolled = jnp.roll(sent, off, axis=0)
            if sc is not None:
                rolled = jnp.where(payload_ok[:, j, None], rolled,
                                   jnp.uint32(0))
            news = rolled & ~have_start & ~claimed
            claimed = claimed | news
            if sc is not None:
                # P2/P4 credit the first deliverer only (later copies are
                # dropped at the seen-cache, pubsub.go:851-868); P3 also
                # counts same-tick near-first copies from mesh members
                # (deliveries window, score.go:684-818)
                fd_add[j] = _popcount_rows(news & valid_words)
                md_new[j] = _popcount_rows(rolled & valid_words
                                           & ~have_start)
                inv_add[j] = _popcount_rows(news & ~valid_words)
        heard_new = claimed & ~injected
        new_mesh_bits = jnp.where(sub[:, None], heard_new, jnp.uint32(0))

        # -- 3. lazy gossip (IHAVE/IWANT collapsed to one exchange) -----
        # advertise ids seen in the last HistoryGossip windows; targets =
        # random non-mesh subscribed candidates, max(Dlazy, factor*elig),
        # both sides above the gossip threshold (gossipsub.go:1656-1712)
        adv = jax.lax.reduce_or(state.recent, axes=(1,)) | injected
        if sc is not None:
            adv = jnp.where(params.sybil[:, None], adv, adv & valid_words)
        elig = params.cand_subscribed & ~state.mesh & ~state.fanout
        elig = elig & sub[:, None]          # only subscribed peers gossip
        if sc is not None:
            elig = elig & gossip_ok
        n_elig = elig.sum(axis=1, dtype=jnp.int32)
        n_gossip = jnp.maximum(
            jnp.int32(cfg.d_lazy),
            (cfg.gossip_factor * n_elig.astype(jnp.float32)).astype(
                jnp.int32))
        targets = select_k_per_row(elig, n_gossip, k_gossip)
        if sc is not None and sc.sybil_ihave_spam:
            # IHAVE-spamming sybils advertise ids they never deliver
            # (gossipsub_spam_test.go:135): their gossip carries nothing,
            # and each spammed peer records a broken promise -> P7
            # (gossip_tracer.go:48-117, applyIwantPenalties)
            sybil_send = params.sybil[:, None] & params.cand_subscribed
            targets = jnp.where(params.sybil[:, None], sybil_send, targets)
        claimed_g = claimed
        bp_spam = None
        for c_send, off in enumerate(cfg.offsets):
            j = cfg.cinv[c_send]
            send_mask = targets[:, c_send]
            if sc is not None and sc.sybil_ihave_spam:
                send_mask = send_mask & ~params.sybil
            sent = jnp.where(send_mask[:, None], adv, jnp.uint32(0))
            rolled = jnp.roll(sent, off, axis=0)
            if sc is not None:
                ok = payload_ok[:, j] & gossip_ok[:, j]
                rolled = jnp.where(ok[:, None], rolled, jnp.uint32(0))
            news = rolled & ~have_start & ~claimed_g
            claimed_g = claimed_g | news
            if sc is not None:
                # IWANT-pulled messages go through validation like any
                # other delivery: P2 credit for valid, P4 for invalid
                fd_add[j] = fd_add[j] + _popcount_rows(news & valid_words)
                inv_add[j] = inv_add[j] + _popcount_rows(
                    news & ~valid_words)
        if sc is not None and sc.sybil_ihave_spam:
            # broken-promise bookkeeping: one P7 unit per sybil IHAVE spam
            spam_recv = transfer_mask(
                targets & params.sybil[:, None], cfg)
            bp_spam = spam_recv.astype(jnp.float32)
        new_gossip_bits = jnp.where(sub[:, None], claimed_g & ~claimed,
                                    jnp.uint32(0))

        new_acquired = new_mesh_bits | new_gossip_bits | injected
        have = state.have | new_acquired
        recent = jnp.concatenate(
            [new_acquired[:, None, :], state.recent[:, :-1]], axis=1)

        delivered_now = new_acquired & params.deliver_words
        if sc is not None:
            delivered_now = delivered_now & valid_words
        first_tick = update_first_tick(state.first_tick, delivered_now,
                                       tick)

        # -- 4. heartbeat maintenance -----------------------------------
        mesh, backoff = state.mesh, state.backoff
        in_backoff = backoff > tick
        mesh_before = mesh

        if sc is not None:
            # drop negative-score mesh members first (gossipsub.go:1332)
            neg = mesh & (score < 0)
            mesh = mesh & ~neg
            backoff = jnp.where(neg, tick + cfg.backoff_ticks, backoff)
        else:
            neg = None
        deg = mesh.sum(axis=1, dtype=jnp.int32)

        # graft up to D when deg < Dlo (gossipsub.go:1340-1360);
        # candidates need score >= 0 in v1.1
        can_graft = (params.cand_subscribed & ~mesh & ~in_backoff
                     & sub[:, None])
        if sc is not None:
            can_graft = can_graft & (score >= 0)
        need = jnp.where(deg < cfg.d_lo, cfg.d - deg, 0)
        grafts = select_k_per_row(can_graft, need, k_graft)

        # prune down to D when deg > Dhi.  v1.0: random retention; v1.1:
        # keep the Dscore best by score, then at least Dout outbound,
        # random fill to D (anti-sybil bubble-up, gossipsub.go:1376-1435)
        if sc is None:
            keep = select_k_per_row(mesh, jnp.full_like(deg, cfg.d),
                                    k_prune)
        else:
            rnd = jax.random.uniform(k_prune, (n, C))
            top = select_k_by_priority(mesh, score,
                                       jnp.full_like(deg, cfg.d_score),
                                       tiebreak=rnd)
            out_cols = jnp.broadcast_to(outbound_cols[None, :], (n, C))
            n_out_top = (top & out_cols).sum(axis=1, dtype=jnp.int32)
            need_out = jnp.maximum(0, cfg.d_out - n_out_top)
            out_keep = select_k_by_priority(mesh & ~top & out_cols, rnd,
                                            need_out)
            taken = top | out_keep
            n_taken = taken.sum(axis=1, dtype=jnp.int32)
            fill = select_k_by_priority(mesh & ~taken, rnd,
                                        jnp.maximum(cfg.d - n_taken, 0))
            keep = taken | fill
        prunes = mesh & ~keep & (deg > cfg.d_hi)[:, None]

        if sc is not None:
            # opportunistic grafting: when the mesh's median score sags
            # below the threshold, graft extra high-scoring peers
            # (gossipsub.go:1467-1498); median via sort + one-hot (no
            # gathers)
            do_og = (tick % sc.opportunistic_graft_ticks) == 0
            s_sorted = jnp.sort(jnp.where(mesh, score, jnp.inf), axis=1)
            onehot = (jnp.arange(C)[None, :] == (deg // 2)[:, None])
            median = jnp.where(deg > 0,
                               (jnp.where(onehot, s_sorted, 0.0)).sum(1),
                               0.0)
            og_row = (do_og & (median < sc.opportunistic_graft_threshold)
                      & sub)
            og_elig = (can_graft & ~grafts
                       & (score > median[:, None]))
            og_need = jnp.where(og_row, sc.opportunistic_graft_peers, 0)
            grafts = grafts | select_k_per_row(og_elig, og_need, k_og)

        if sc is not None and sc.sybil_graft_flood:
            # GRAFT-flooding sybils re-graft every tick, ignoring their
            # own backoff (gossipsub_spam_test.go:349)
            sybil_grafts = (params.cand_subscribed & ~mesh
                            & params.sybil[:, None])
            grafts = jnp.where(params.sybil[:, None], sybil_grafts, grafts)

        mesh = (mesh | grafts) & ~prunes
        backoff = jnp.where(prunes, tick + cfg.backoff_ticks, backoff)

        # handshake: partner accepts GRAFT unless unsubscribed, backed
        # off, or (v1.1) negative-scored (handleGraft gossipsub.go:713-
        # 804); PRUNE always removes + backs off (handlePrune :806-838).
        # Negative-score prunes notify the partner too (the reference
        # sends PRUNE for every mesh removal, gossipsub.go:1332-1338).
        graft_recv = transfer_mask(grafts, cfg)
        prune_recv = transfer_mask(prunes if neg is None else prunes | neg,
                                   cfg)
        if sc is not None:
            # graylisted peers' control traffic is dropped outright
            graft_recv = graft_recv & edge_accept
            prune_recv = prune_recv & edge_accept
        backoff_violation = graft_recv & (backoff > tick)
        accept = graft_recv & sub[:, None] & ~(backoff > tick)
        if sc is not None:
            accept = accept & (score >= 0)
        reject = graft_recv & ~accept
        mesh = (mesh | accept) & ~prune_recv
        backoff = jnp.where(prune_recv,
                            jnp.maximum(backoff, tick + cfg.backoff_ticks),
                            backoff)
        # PRUNE response to rejected grafts retracts the optimistic graft
        reject_back = transfer_mask(reject, cfg)
        mesh = mesh & ~reject_back
        backoff = jnp.where(
            reject_back, jnp.maximum(backoff, tick + cfg.backoff_ticks),
            backoff)

        # -- 5. score counter updates + decay ---------------------------
        scores = state.scores
        if sc is not None:
            s0 = state.scores
            fd = jnp.minimum(
                s0.first_deliveries + jnp.stack(fd_add, axis=1),
                sc.first_message_deliveries_cap)
            md = jnp.minimum(
                s0.mesh_deliveries
                + jnp.stack(md_new, axis=1) * mesh_before,
                sc.mesh_message_deliveries_cap)
            inv = s0.invalid_deliveries + jnp.stack(inv_add, axis=1)
            # P3b: an edge pruned while active with a delivery deficit
            # keeps the deficit² as a sticky penalty (score.go Prune)
            removed = mesh_before & ~mesh
            was_active = (s0.time_in_mesh
                          > sc.mesh_message_deliveries_activation)
            deficit = jnp.maximum(
                0.0, sc.mesh_message_deliveries_threshold - md)
            mfp = s0.mesh_failure_penalty + jnp.where(
                removed & was_active, deficit * deficit, 0.0)
            # P7: backoff violations + broken gossip promises
            bp = s0.behaviour_penalty + backoff_violation.astype(
                jnp.float32)
            if bp_spam is not None:
                bp = bp + bp_spam
            # decay (refreshScores, score.go:495-556)
            def dk(x, decay):
                x = x * decay
                return jnp.where(x < sc.decay_to_zero, 0.0, x)
            scores = ScoreState(
                time_in_mesh=jnp.where(mesh, s0.time_in_mesh + 1.0, 0.0),
                first_deliveries=dk(fd, sc.first_message_deliveries_decay),
                mesh_deliveries=dk(md, sc.mesh_message_deliveries_decay),
                mesh_failure_penalty=dk(mfp, sc.mesh_failure_penalty_decay),
                invalid_deliveries=dk(
                    inv, sc.invalid_message_deliveries_decay),
                behaviour_penalty=dk(bp, sc.behaviour_penalty_decay),
            )

        new_state = GossipState(
            mesh=mesh, fanout=fanout, last_pub=last_pub, backoff=backoff,
            have=have, recent=recent, first_tick=first_tick, scores=scores,
            key=key, tick=tick + 1)
        return new_state, delivered_now

    return step


def _popcount_rows(words: jnp.ndarray) -> jnp.ndarray:
    """Total set bits per row: uint32 [N, W] -> f32 [N]."""
    return jax.lax.population_count(words).sum(
        axis=1, dtype=jnp.int32).astype(jnp.float32)


# --------------------------------------------------------------------------
# Runners / metrics (mirror models/floodsub.py)
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2, 3))
def gossip_run(params: GossipParams, state: GossipState, n_ticks: int,
               step) -> GossipState:
    def body(s, _):
        return step(params, s)[0], None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4))
def gossip_run_curve(params: GossipParams, state: GossipState, n_ticks: int,
                     step, n_msgs: int):
    """Run n_ticks collecting per-tick delivered counts [n_ticks, M]."""
    def body(s, _):
        s2, delivered = step(params, s)
        return s2, count_bits_per_position(delivered, n_msgs)
    state, counts = jax.lax.scan(body, state, None, length=n_ticks)
    return state, counts


def first_tick_matrix(state: GossipState, m: int) -> jnp.ndarray:
    return first_tick_to_matrix(state.first_tick, m)


def reach_counts(params: GossipParams, state: GossipState) -> jnp.ndarray:
    return reach_counts_from_first_tick(state.first_tick,
                                        params.publish_tick.shape[0])


def mesh_degrees(state: GossipState) -> jnp.ndarray:
    return state.mesh.sum(axis=1, dtype=jnp.int32)


def mesh_symmetry_fraction(state: GossipState,
                           cfg: GossipSimConfig) -> jnp.ndarray:
    """Fraction of mesh edges whose partner also has the edge (after the
    GRAFT/PRUNE handshake settles this should approach 1)."""
    partner = transfer_mask(state.mesh, cfg)
    agree = (state.mesh & partner).sum()
    total = state.mesh.sum()
    return agree / jnp.maximum(total, 1)
