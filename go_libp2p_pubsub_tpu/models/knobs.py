"""Config-as-data: the SimKnobs device pytree (ROADMAP direction 2).

Every numeric ``GossipSimConfig`` field whose value does NOT determine
array shapes is liftable from a baked compile-time constant to a traced
f32/i32 SCALAR LEAF riding the sim params — so ONE compiled executable
serves arbitrary protocol parameter points, and ``stack_trees``/``vmap``
batches advance replicas with HETEROGENEOUS configs (not just seeds) in
one dispatch.  PR 7 proved the pattern on the four ScoreKnobs defense
parameters; this module generalizes it to the whole liftable surface:

- the degree family ``d / d_lo / d_hi / d_score / d_out / d_lazy``
  (consumed in popcount compares and selection counts — integer data),
- ``gossip_factor`` (the emitGossip coverage fraction, f32),
- ``gossip_retransmission`` (the IWANT serve-budget multiplier),
- ``backoff_ticks`` / ``fanout_ttl_ticks`` (tick-count compares),
- the existing ``ScoreKnobs`` defense sub-tree (folded in as ``score``),
- the ``FaultSchedule`` link-drop rate (``drop_prob`` — already a
  traced ``FaultParams`` leaf; the knob surface overrides its value, so
  sweeps vary loss rates per replica under one schedule shape).  Churn
  rates ride the ``[N, K]`` down-interval tables, which are per-replica
  data already — pad every replica to one K with ``(p, 0, 0)`` no-ops.

Shape-bearing fields stay STATIC and are rejected by name
(``KnobStaticFieldError``): ``offsets`` (the circulant topology — roll
offsets are baked into every edge transfer), ``n_topics`` (residue-
class layout), ``history_length`` / ``history_gossip`` (the mcache ring
shape [Hg, W, N] and its baked expiry divisor), and the telemetry
histogram bucket shapes (TelemetryConfig, not reachable from here).
Mode toggles (``paired_topics``, ``px_rotation``,
``binomial_gossip_sampling``) select compiled code paths and stay
static too.

Bit-identity contract: a ``SimKnobs`` built at the config's own values
produces the EXACT baked trajectory (integer compares and f32 products
are value-equal; tests/test_knobs.py pins all execution paths), so
arming knobs costs nothing but the scalar operands.

Validation is host-side and eager: the same ordering invariants
``GossipSimConfig.__post_init__`` enforces (Dlo <= D <= Dhi, Dscore <=
D, Dout < Dlo and Dout <= D/2, Dhi < C, backoff int16 range, ...)
apply to every knob point, with the bad field named.
"""

from __future__ import annotations

from typing import ClassVar

import jax.numpy as jnp
from flax import struct

__all__ = [
    "SIM_KNOB_FIELDS",
    "FAULT_KNOB_FIELDS",
    "STATIC_KNOB_REASONS",
    "KnobStaticFieldError",
    "SimKnobs",
    "split_knob_overrides",
    "make_sim_knobs",
    "knob_values",
]


#: the liftable GossipSimConfig scalar surface, in SimKnobs field order.
#: Integer-valued fields ride as i32 scalars, gossip_factor as f32.
SIM_KNOB_FIELDS = (
    "d", "d_lo", "d_hi", "d_score", "d_out", "d_lazy",
    "gossip_factor", "gossip_retransmission",
    "backoff_ticks", "fanout_ttl_ticks",
)

#: FaultSchedule knobs: traced overrides applied to the compiled
#: FaultParams leaves (make_gossip_sim), not carried on SimKnobs.
FAULT_KNOB_FIELDS = ("drop_prob",)

#: DelayConfig knobs (round 13, models/delays.py): traced overrides
#: applied to the compiled DelayParams leaves by make_gossip_sim —
#: the heartbeat/RTT ratio sweeps recompile-free, exactly like
#: drop_prob.  Requires a DelayConfig on the sim (the delay-line code
#: path must compile in; the line depth k_slots stays shape-bearing).
DELAY_KNOB_FIELDS = ("delay_base", "delay_jitter")

#: shape-bearing / mode-selecting fields, rejected BY NAME with the
#: reason they must stay compile-time (the sweepd request validator and
#: make_sim_knobs share this table).
STATIC_KNOB_REASONS = {
    "offsets": "the circulant topology: ring offsets are baked into "
               "every edge-transfer roll and the kernel DMA plan",
    "n_topics": "the residue-class layout: membership, deliver masks "
                "and offset moduli are built from it",
    "history_length": "the mcache expiry divisor is baked with the "
                      "ring layout (serve-ledger ceil-div)",
    "history_gossip": "shapes the [Hg, W, N] recent ring",
    "paired_topics": "selects the two-mesh compiled step",
    "px_rotation": "selects the PX rotation epilogue code path",
    "binomial_gossip_sampling": "selects the sampling backend "
                                "(Bernoulli vs rank-compare code path)",
    "max_ihave_length": "a build-time static invariant, never run-time",
    "max_ihave_messages": "a build-time static invariant, never "
                          "run-time",
    # the delay-line depth (models/delays.py DelayConfig.k_slots) is
    # shape-bearing: it sizes the K-slot circular delay-line state
    # carried through the scan.  Both spellings rejected by name.
    "k_slots": "shapes the [K, ...] delay-line state carried through "
               "the scan (models/delays.py) — sweep delay_base / "
               "delay_jitter instead, within the compiled depth",
    "delay_k_slots": "shapes the [K, ...] delay-line state carried "
                     "through the scan (models/delays.py) — sweep "
                     "delay_base / delay_jitter instead, within the "
                     "compiled depth",
    # telemetry histogram shapes live on TelemetryConfig, but name the
    # common ones so a sweepd request that tries them gets the reason
    "latency_buckets": "shapes the telemetry latency histogram output",
    "degree_buckets": "shapes the telemetry degree histogram output",
    "score_bucket_edges": "shapes the telemetry score histogram output",
}

_INT_KNOBS = frozenset(SIM_KNOB_FIELDS) - {"gossip_factor"}


class KnobStaticFieldError(ValueError):
    """A shape-bearing (or mode-selecting) config field was passed as a
    knob.  The message names the field and why it must stay static."""


@struct.dataclass
class SimKnobs:
    """Traced protocol-parameter overrides: every leaf is a SCALAR
    device array (i32 for the integer family, f32 for gossip_factor),
    so ``stack_trees`` turns a list of knob points into [B] vectors the
    vmapped step maps over — B *different* protocol configs, one
    compiled executable.  ``score`` folds the PR-7 ScoreKnobs defense
    sub-tree in (None when no score overrides ride).

    Build through ``make_sim_knobs`` (validated); fields left
    unspecified take the config's own values, which is bit-identical
    to the baked step (pinned by tests/test_knobs.py)."""

    d: jnp.ndarray                      # i32 []
    d_lo: jnp.ndarray                   # i32 []
    d_hi: jnp.ndarray                   # i32 []
    d_score: jnp.ndarray                # i32 []
    d_out: jnp.ndarray                  # i32 []
    d_lazy: jnp.ndarray                 # i32 []
    gossip_factor: jnp.ndarray          # f32 []
    gossip_retransmission: jnp.ndarray  # i32 []
    backoff_ticks: jnp.ndarray          # i32 []
    fanout_ttl_ticks: jnp.ndarray       # i32 []
    # the ScoreKnobs defense sub-tree (models/gossipsub.py), None when
    # no score-parameter overrides ride this knob point
    score: object = None

    # Machine-readable contract (tools/graftlint/contracts.py): every
    # knob leaf must be provably "traced" on each path — jaxpr
    # IDENTICAL across two knob values (no retrace) while the build
    # leaves differ.  gossip_retransmission is kernel-"refused": the
    # only config where it is live (sybil_iwant_spam) computes the
    # serve budget in-kernel from the baked constant, and the kernel
    # refuses knob points there by name (message-matched probe).
    PATHS: ClassVar[tuple[str, ...]] = ("xla", "kernel")
    CONTRACT: ClassVar[dict[str, object]] = {
        "d": "traced",
        "d_lo": "traced",
        "d_hi": "traced",
        "d_score": "traced",
        "d_out": "traced",
        "d_lazy": "traced",
        "gossip_factor": "traced",
        "gossip_retransmission": {"xla": "traced",
                                  "kernel": "refused"},
        "backoff_ticks": "traced",
        "fanout_ttl_ticks": "traced",
        "score": "traced",
    }


def split_knob_overrides(overrides: dict, score_fields=None) -> tuple:
    """Partition a raw knob dict into (protocol, score, fault, delay)
    override dicts, rejecting static fields by name and unknown fields
    with the full valid-knob list.  ``score_fields`` defaults to
    gossipsub's SCORE_KNOB_FIELDS (passed in to avoid the import
    cycle)."""
    if score_fields is None:
        from . import gossipsub as _gs
        score_fields = _gs.SCORE_KNOB_FIELDS
    proto, score, fault, delay = {}, {}, {}, {}
    for key, val in dict(overrides).items():
        if key in STATIC_KNOB_REASONS:
            raise KnobStaticFieldError(
                f"sim_knobs: {key!r} is a static (shape-bearing) "
                f"config field and cannot be swept as a knob — "
                f"{STATIC_KNOB_REASONS[key]}.  Recompile with a new "
                "config to change it.")
        if key in SIM_KNOB_FIELDS:
            proto[key] = val
        elif key in score_fields:
            score[key] = val
        elif key in FAULT_KNOB_FIELDS:
            fault[key] = val
        elif key in DELAY_KNOB_FIELDS:
            delay[key] = val
        else:
            all_knobs = (SIM_KNOB_FIELDS + tuple(score_fields)
                         + FAULT_KNOB_FIELDS + DELAY_KNOB_FIELDS)
            raise ValueError(
                f"sim_knobs: unknown knob {key!r} — sweepable knobs "
                f"are {all_knobs}")
    return proto, score, fault, delay


def _validate_point(vals: dict, n_candidates: int,
                    px_candidates: int | None = None) -> None:
    """The GossipSimConfig.__post_init__ ordering invariants, applied
    to a resolved knob point (host floats/ints), naming the bad
    field(s)."""
    d, d_lo, d_hi = vals["d"], vals["d_lo"], vals["d_hi"]
    if not (d_lo <= d <= d_hi):
        raise ValueError(
            f"sim_knobs: need d_lo <= d <= d_hi (got {d_lo}, {d}, "
            f"{d_hi}; gossipsub.go:33-35)")
    if vals["d_score"] > d:
        raise ValueError(
            f"sim_knobs: need d_score <= d (got {vals['d_score']} > "
            f"{d})")
    if vals["d_out"] >= d_lo or vals["d_out"] > d // 2:
        raise ValueError(
            f"sim_knobs: need d_out < d_lo and d_out <= d/2 (got "
            f"d_out={vals['d_out']}; gossipsub.go:266-272)")
    ceiling = n_candidates if px_candidates is None else px_candidates
    if d_hi >= ceiling:
        raise ValueError(
            f"sim_knobs: need d_hi < {'px_candidates' if px_candidates is not None else 'C'}"
            f"={ceiling} (got d_hi={d_hi}) — the selection space "
            "cannot satisfy the degree bound")
    if not (1 <= vals["backoff_ticks"] <= 32767):
        raise ValueError(
            f"sim_knobs: backoff_ticks={vals['backoff_ticks']} must "
            "fit int16 remaining-tick storage (1..32767)")
    if vals["gossip_retransmission"] < 1:
        raise ValueError(
            f"sim_knobs: gossip_retransmission="
            f"{vals['gossip_retransmission']} must be >= 1")
    if vals["fanout_ttl_ticks"] < 1:
        raise ValueError(
            f"sim_knobs: fanout_ttl_ticks={vals['fanout_ttl_ticks']} "
            "must be >= 1")
    if vals["d_lazy"] < 0:
        raise ValueError(
            f"sim_knobs: d_lazy={vals['d_lazy']} must be >= 0")
    if not (0.0 <= vals["gossip_factor"] <= 1.0):
        raise ValueError(
            f"sim_knobs: gossip_factor={vals['gossip_factor']} "
            "outside [0, 1]")


def knob_values(cfg, overrides: dict | None = None) -> dict:
    """The resolved host-side values of a knob point over ``cfg``
    (override where given, config default otherwise)."""
    overrides = overrides or {}
    out = {}
    for f in SIM_KNOB_FIELDS:
        v = overrides.get(f, getattr(cfg, f))
        out[f] = float(v) if f == "gossip_factor" else int(v)
    return out


def make_sim_knobs(cfg, score_cfg=None, overrides: dict | None = None,
                   px_candidates: int | None = None) -> SimKnobs:
    """Build a validated SimKnobs point over ``cfg``.

    ``overrides`` may mix protocol knobs (SIM_KNOB_FIELDS) and
    ScoreKnobs defense fields (folded into the ``score`` sub-tree;
    require ``score_cfg``).  Static fields raise KnobStaticFieldError
    by name; every resolved point passes the config's own ordering
    invariants."""
    from . import gossipsub as _gs

    proto, score_kv, fault, delay = split_knob_overrides(
        overrides or {}, _gs.SCORE_KNOB_FIELDS)
    if fault or delay:
        raise ValueError(
            "sim_knobs: fault/delay knobs (drop_prob, delay_base, "
            "delay_jitter) are applied to the compiled FaultParams/"
            "DelayParams by make_gossip_sim — pass them through its "
            "sim_knobs dict, not make_sim_knobs directly")
    vals = knob_values(cfg, proto)
    _validate_point(vals, cfg.n_candidates, px_candidates)

    if score_kv and score_cfg is None:
        raise ValueError(
            "sim_knobs: score-parameter knobs "
            f"{sorted(score_kv)} require score_cfg")
    score = None
    if score_cfg is not None:
        # the score sub-tree is ALWAYS armed on scored sims (defaults
        # = the score_cfg values, bit-identical to baked) so stacked
        # replica batches mixing defended and reference points share
        # one pytree structure (stack_trees needs matching leaves)
        kv = {f: float(score_kv.get(f, getattr(score_cfg, f)))
              for f in _gs.SCORE_KNOB_FIELDS}
        for f in ("invalid_message_deliveries_weight",
                  "behaviour_penalty_weight"):
            if kv[f] > 0:
                raise ValueError(f"sim_knobs: {f} must be <= 0")
        if not (kv["graylist_threshold"]
                <= score_cfg.publish_threshold
                <= kv["gossip_threshold"] <= 0):
            raise ValueError(
                "sim_knobs: need graylist <= publish (static) <= "
                "gossip threshold <= 0")
        score = _gs.ScoreKnobs(
            **{f: jnp.float32(kv[f]) for f in _gs.SCORE_KNOB_FIELDS})

    leaf = {f: (jnp.float32(vals[f]) if f == "gossip_factor"
                else jnp.int32(vals[f]))
            for f in SIM_KNOB_FIELDS}
    return SimKnobs(score=score, **leaf)
