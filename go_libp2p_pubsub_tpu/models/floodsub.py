"""FloodSub simulator: every-peer-at-once flood dissemination.

The vectorized counterpart of the protocol core's FloodSubRouter
(core/floodsub.py; reference /root/reference/floodsub.go): one jitted
``step`` advances one virtual tick (= one network hop) for ALL simulated
peers simultaneously.  Message possession is bitpacked (32 message slots per
uint32 word), subscriptions/relays become forward/deliver masks, and
first-delivery ticks are recorded per (peer, message) so
reachability-vs-hops curves fall out as histograms.

Layout: peer-minor — possession words are uint32 [W, N] and first-tick
records int16 [W, 32, N], so the peer axis sits on the TPU vector lanes
and each word row rolls as a contiguous 1D array (see _delivery.py and
PERF_NOTES.md).  State is a flax pytree; sharding the peer axis over a
device mesh makes the same ``step`` run multi-chip unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ._batch import index_trees, stack_trees, tree_copy  # noqa: F401
#   (re-exported: companions of the donated/batched runners)
from ..ops.graph import (
    WORD_BITS,
    count_bits_per_position,
    pack_bits,
    pack_bits_pm,
    propagate_circulant,
    propagate_pm,
)
from ._delivery import (
    first_tick_to_matrix,
    reach_by_hops_from_first_tick,
    reach_counts_from_first_tick,
    update_first_tick,
)
from . import delays as _delays
from . import faults as _faults
from . import invariants as _invariants
from . import telemetry as _telemetry


@struct.dataclass
class FloodParams:
    """Static (per-simulation) arrays.  nbrs/nbr_mask are None for
    circulant topologies (offsets are compile-time constants instead)."""

    nbrs: jnp.ndarray          # int32 [N, K] or None
    nbr_mask: jnp.ndarray      # bool  [N, K] or None
    fwd_words: jnp.ndarray     # uint32 [W, N]: will forward bit m
    deliver_words: jnp.ndarray # uint32 [W, N]: counts as delivery for bit m
    origin_words: jnp.ndarray  # uint32 [W, N]: bit m set at origin[m]
    publish_tick: jnp.ndarray  # int32 [M]
    # compiled fault schedule (models/faults.py) — circulant step only
    faults: _faults.FaultParams | None = None
    # round-13 event-driven time (models/delays.py): per-edge delay +
    # jitter.  Floodsub's sender is a pure function of (possession,
    # tick), so the delay line compiles to the state's source-history
    # RING plus per-lag replayed send draws — see delays.py.
    delays: _delays.DelayParams | None = None


@struct.dataclass
class FloodState:
    have: jnp.ndarray        # uint32 [W, N]
    first_tick: jnp.ndarray  # int16 [W, 32, N], -1 = never delivered
    # (word-aligned layout: bit j of word w is message w*32+j; stored
    # unreshaped so the hot-loop update never materializes a relayout)
    tick: jnp.ndarray        # int32 scalar
    # in-scan invariant-checker carry (models/invariants.py, round 11)
    # — None (default) keeps the pytree identical to the pre-invariant
    # state; invariants.attach(state) arms them
    inv_viol: jnp.ndarray | None = None      # uint32 []
    inv_first: jnp.ndarray | None = None     # int32 []
    # round-13 source-history ring (delay-armed sims only): slot
    # t mod K holds the possession words at the START of tick t, so
    # lag-l arrivals replay the tick-(t-l) sends exactly
    src_ring: jnp.ndarray | None = None      # uint32 [K, W, N]


def make_flood_sim(nbrs: np.ndarray, nbr_mask: np.ndarray, subs: np.ndarray,
                   relays: np.ndarray | None, msg_topic: np.ndarray,
                   msg_origin: np.ndarray, msg_publish_tick: np.ndarray,
                   track_first_tick: bool = True,
                   fault_schedule: _faults.FaultSchedule | None = None,
                   fault_offsets=None,
                   delays: _delays.DelayConfig | None = None):
    """Build (params, state) for a flood simulation.

    subs/relays: bool [N, T]; msg_*: [M] arrays describing the message table.
    track_first_tick=False drops the per-(peer, message) delivery-tick array
    (use flood_run_curve's per-tick counts instead) — the fast path.

    fault_schedule (models/faults.py) injects churn/link-loss/partition
    events.  On circulant topologies (nbrs=None) pass the step's
    offsets as ``fault_offsets``; on GATHER topologies (round 10) the
    schedule compiles against the nbrs table itself
    (compile_faults_gather — per-undirected-pair link coins, baked
    partition-crossing slots) and flood_step honors it.

    delays (round 13, models/delays.py) makes every hop take
    ``base + jitter-draw`` ticks: the circulant and gather cores both
    honor it through the source-history ring (``DelayConfig(1, 0, 1)``
    is bit-identical to the pre-delay step, pinned).
    """
    n = subs.shape[0]
    m = len(msg_topic)
    if relays is None:
        relays = np.zeros_like(subs)
    if nbrs is None:
        nbrs_j = nbr_mask_j = None
    else:
        nbrs_j, nbr_mask_j = jnp.asarray(nbrs), jnp.asarray(nbr_mask)

    sub_bits = subs[:, msg_topic]                  # [N, M]
    relay_bits = relays[:, msg_topic]
    origin_bits = np.zeros((n, m), dtype=bool)
    origin_bits[msg_origin, np.arange(m)] = True

    fparams = None
    if fault_schedule is not None:
        if fault_schedule.n_peers != n:
            raise ValueError(
                f"fault_schedule.n_peers={fault_schedule.n_peers} != "
                f"sim peer count {n}")
        if fault_schedule.cold_restart:
            # the refusal string is defined once, in the capability
            # planner (models/plan.py)
            from .plan import MSG_FLOOD_COLD_RESTART
            raise ValueError(MSG_FLOOD_COLD_RESTART)
        if nbrs is not None:
            fparams = _faults.compile_faults_gather(fault_schedule,
                                                    nbrs, nbr_mask)
        else:
            if fault_offsets is None:
                raise ValueError(
                    "fault_schedule needs fault_offsets (the circulant "
                    "offsets the step was built with)")
            fparams = _faults.compile_faults(fault_schedule,
                                             fault_offsets,
                                             pack_links=False)

    # a peer forwards what it is subscribed/relaying for, plus its own
    # publishes (publish-without-subscribe floods too, floodsub.go:76-100)
    fwd = sub_bits | relay_bits | origin_bits
    params = FloodParams(
        nbrs=nbrs_j,
        nbr_mask=nbr_mask_j,
        fwd_words=pack_bits_pm(jnp.asarray(fwd)),
        deliver_words=pack_bits_pm(jnp.asarray(sub_bits)),
        origin_words=pack_bits_pm(jnp.asarray(origin_bits)),
        publish_tick=jnp.asarray(msg_publish_tick, dtype=jnp.int32),
        faults=fparams,
        delays=(None if delays is None
                else _delays.compile_delays(delays)),
    )
    w = params.fwd_words.shape[0]
    state = FloodState(
        have=jnp.zeros((w, n), dtype=jnp.uint32),
        first_tick=(jnp.full((w, WORD_BITS, n), -1, dtype=jnp.int16)
                    if track_first_tick else None),
        tick=jnp.zeros((), dtype=jnp.int32),
        src_ring=(None if delays is None
                  else jnp.zeros((int(delays.k_slots), w, n),
                                 dtype=jnp.uint32)),
    )
    return params, state


def flood_step(params: FloodParams, state: FloodState) -> FloodState:
    """One virtual tick over a GATHER topology: inject due publishes,
    propagate one hop, record first deliveries.  Pure function —
    jit/shard_map friendly.  Honors ``params.faults`` since round 10
    (compile_faults_gather: a down peer neither sends, receives, nor
    injects; undirected links drop on canonical-pair coins; partition
    windows cut the baked crossing slots)."""
    return make_gather_step_core()(params, state)[0]


def make_gather_step_core(telemetry:
                          "_telemetry.TelemetryConfig | None" = None,
                          invariants:
                          "_invariants.InvariantConfig | None" = None):
    """(params, state) -> (state, delivered_words) over a gather
    (nbrs-table) topology — round 10 twin of make_circulant_step_core.

    Honors ``params.faults`` (gather-compiled, see flood_step).  With
    ``telemetry`` the core returns ``(state, delivered_words,
    TelemetryFrame)`` carrying floodsub's frame subset: payload copies
    sent (sender-side, per live table slot), duplicates suppressed,
    estimated payload bytes, the delivery-latency histogram, and the
    fault counters — gossip/mesh/score fields stay zero.  The
    fault-free telemetry-off build compiles the exact fused
    propagate_pm hop; counting runs the same gather with the masks
    visible (state trajectory bit-identical either way).

    With ``invariants`` (models/invariants.py, round 11) the core
    folds floodsub's applicable check subset — the ``delivery`` group
    — into the armed state's inv carry (pure readout, trajectory
    bit-identical; ``None`` compiles the exact pre-invariant core)."""
    tel = telemetry
    ws = _telemetry.wire_sizes(tel) if tel is not None else None
    pc = jax.lax.population_count

    def delayed_gather(params: FloodParams, state: FloodState):
        # round-13 event-driven hop over the gather table: lag-l
        # arrivals replay the tick-(t-l) sends from the source-history
        # ring, keeping the slots whose sampled delay was exactly l+1
        # (models/delays.py — the table-path twin of the circulant
        # delayed core below)
        dlp = params.delays
        K = dlp.k_slots
        fp = params.faults
        tick = state.tick
        W = state.have.shape[0]
        alive = aw_now = None
        if fp is not None:
            alive = _faults.alive_mask(fp, tick)
            aw_now = _faults.alive_word(alive)
        count = tel is not None and tel.counters
        sent_cnt = recv_cnt = jnp.int32(0) if count else None
        heard = jnp.zeros_like(state.have)
        ok_now = src_now = None
        for lag in range(K):
            t_s = tick - lag
            src = (state.have if lag == 0
                   else jax.lax.dynamic_index_in_dim(
                       state.src_ring, jnp.mod(t_s, K), axis=0,
                       keepdims=False))
            src = src & params.fwd_words
            ok = params.nbr_mask
            if fp is not None:
                src = src & _faults.alive_word(
                    _faults.alive_mask(fp, t_s))[None, :]
                link_s = _faults.link_ok_gather(fp, params.nbrs, t_s)
                if link_s is not None:
                    ok = ok & link_s
            if lag == 0:
                ok_now, src_now = ok, src
            okl = ok & _delays.arrive_now(dlp, params.nbrs.shape,
                                          t_s, lag)
            gathered = src.at[:, params.nbrs].get(
                mode="fill", fill_value=0)                 # [W, N, K]
            gathered = jnp.where(okl[None, :, :], gathered,
                                 jnp.uint32(0))
            arr = jnp.zeros_like(state.have)
            for k in range(params.nbrs.shape[1]):
                arr = arr | gathered[:, :, k]
            if aw_now is not None:
                arr = arr & aw_now[None, :]                # receiver up
            heard = heard | arr
            if count:
                recv = (gathered if aw_now is None
                        else gathered & aw_now[None, :, None])
                recv_cnt = recv_cnt + pc(recv).sum(dtype=jnp.int32)
        if count:
            # payload copies SENT this tick (every delay class): the
            # full tick-t send set, before delay routing
            g_now = src_now.at[:, params.nbrs].get(
                mode="fill", fill_value=0)
            g_now = jnp.where(ok_now[None, :, :], g_now,
                              jnp.uint32(0))
            sent_cnt = pc(g_now).sum(dtype=jnp.int32)
        ring = jax.lax.dynamic_update_slice_in_dim(
            state.src_ring, state.have[None], jnp.mod(tick, K),
            axis=0)
        new_state, delivered = _finish_step(params, state, heard,
                                            alive=alive,
                                            src_ring=ring)
        if tel is None:
            return new_state, delivered
        kw_f = {}
        if count:
            accepted = (heard & ~state.have
                        & (params.fwd_words | params.deliver_words))
            kw_f.update(
                payload_sent=sent_cnt,
                dup_suppressed=recv_cnt - pc(accepted).sum(
                    dtype=jnp.int32))
            if tel.wire:
                kw_f["bytes_payload"] = (
                    sent_cnt.astype(jnp.float32)
                    * float(ws.payload_frame))
        if tel.latency_hist:
            kw_f["latency_hist"] = _telemetry.latency_histogram(
                delivered, params.publish_tick, tick,
                tel.latency_buckets)
        if tel.faults and fp is not None:
            kw_f["down_peers"] = (~alive).sum(dtype=jnp.int32)
            if fp.drop_prob is not None or fp.cross_nk is not None:
                link_now = _faults.link_ok_gather(fp, params.nbrs,
                                                  tick)
                kw_f["dropped_edge_ticks"] = (
                    (~link_now & params.nbr_mask).sum(
                        dtype=jnp.int32) // 2)
        return new_state, delivered, _telemetry.make_frame(**kw_f)

    def core(params: FloodParams, state: FloodState):
        if params.delays is not None:
            return delayed_gather(params, state)
        fp = params.faults
        src = state.have & params.fwd_words                # [W, N]
        alive = aw = link_up = None
        if fp is not None:
            alive = _faults.alive_mask(fp, state.tick)
            aw = _faults.alive_word(alive)
            src = src & aw[None, :]                        # sender up
            link_up = _faults.link_ok_gather(fp, params.nbrs,
                                             state.tick)
        if fp is None and tel is None:
            heard = propagate_pm(src, params.nbrs, params.nbr_mask)
            return _finish_step(params, state, heard)
        if fp is not None and link_up is None and tel is None:
            # pure churn: every table slot carries, so the hop IS the
            # fused propagation kernel — only the endpoints are masked
            # (twin of the circulant core's pure-churn case)
            heard = propagate_pm(src, params.nbrs,
                                 params.nbr_mask) & aw[None, :]
            return _finish_step(params, state, heard, alive=alive)
        ok = params.nbr_mask if link_up is None \
            else params.nbr_mask & link_up                 # [N, K]
        gathered = src.at[:, params.nbrs].get(
            mode="fill", fill_value=0)                     # [W, N, K]
        gathered = jnp.where(ok[None, :, :], gathered, jnp.uint32(0))
        heard = jnp.zeros_like(src)
        for k in range(params.nbrs.shape[1]):
            heard = heard | gathered[:, :, k]
        if aw is not None:
            heard = heard & aw[None, :]                    # receiver up
        new_state, delivered = _finish_step(params, state, heard,
                                            alive=alive)
        if tel is None:
            return new_state, delivered
        kw_f = {}
        if tel.counters:
            sent_cnt = pc(gathered).sum(dtype=jnp.int32)
            recv = (gathered if aw is None
                    else gathered & aw[None, :, None])
            recv_cnt = pc(recv).sum(dtype=jnp.int32)
            accepted = (heard & ~state.have
                        & (params.fwd_words | params.deliver_words))
            kw_f.update(
                payload_sent=sent_cnt,
                dup_suppressed=recv_cnt - pc(accepted).sum(
                    dtype=jnp.int32))
            if tel.wire:
                kw_f["bytes_payload"] = (
                    sent_cnt.astype(jnp.float32)
                    * float(ws.payload_frame))
        if tel.latency_hist:
            kw_f["latency_hist"] = _telemetry.latency_histogram(
                delivered, params.publish_tick, state.tick,
                tel.latency_buckets)
        if tel.faults and fp is not None:
            kw_f["down_peers"] = (~alive).sum(dtype=jnp.int32)
            if link_up is not None:
                # two table slots per undirected edge on a symmetric
                # table; halve like the circulant paths
                kw_f["dropped_edge_ticks"] = (
                    (~link_up & params.nbr_mask).sum(
                        dtype=jnp.int32) // 2)
        return new_state, delivered, _telemetry.make_frame(**kw_f)

    if invariants is not None:
        return _invariants.wrap_step_delivery(core, invariants,
                                              "floodsub (gather)")
    return core


def make_circulant_flood_step(offsets):
    """A flood step over a circulant topology (offsets baked in as
    compile-time constants; the hop is rolls, not gathers)."""
    core = make_circulant_step_core(offsets)

    def step(params: FloodParams, state: FloodState) -> FloodState:
        return core(params, state)[0]

    return step


def _finish_step(params: FloodParams, state: FloodState,
                 heard: jnp.ndarray,
                 alive: jnp.ndarray | None = None,
                 src_ring: jnp.ndarray | None = None
                 ) -> tuple[FloodState, jnp.ndarray]:
    # the hop used what peers had at the END of the previous tick —
    # a publish at tick t reaches direct neighbors at t+1
    new_bits = heard & ~state.have
    accepted = new_bits & (params.fwd_words | params.deliver_words)

    # then inject messages whose publish tick is now
    due = pack_bits(params.publish_tick == state.tick)          # [W]
    injected = params.origin_words & due[:, None] & ~state.have
    if alive is not None:
        # a down origin does not publish: the message is lost, not
        # deferred (the node was off at its publish tick)
        injected = injected & _faults.alive_word(alive)[None, :]
    have = state.have | accepted | injected

    # delivery accounting (origin's own publish counts at inject tick)
    delivered_now = (accepted & params.deliver_words) | (
        injected & params.deliver_words)
    first_tick = update_first_tick(state.first_tick, delivered_now,
                                   state.tick)

    new_state = FloodState(have=have, first_tick=first_tick,
                           tick=state.tick + 1,
                           inv_viol=state.inv_viol,
                           inv_first=state.inv_first,
                           src_ring=(src_ring if src_ring is not None
                                     else state.src_ring))
    return new_state, delivered_now


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def flood_run(params: FloodParams, state: FloodState, n_ticks: int,
              step_fn=flood_step) -> FloodState:
    """Run n_ticks steps under one jit (lax.scan keeps the trace compact).

    The state carry is DONATED — the scan reuses the input's buffers
    instead of holding two full copies live; callers that need the
    input state afterwards pass tree_copy(state) (models/_batch.py)."""
    def body(s, _):
        return step_fn(params, s), None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def flood_run_curve(params: FloodParams, state: FloodState, n_ticks: int,
                    step_core, n_msgs: int):
    """Run n_ticks steps collecting per-tick delivered counts.

    step_core: (params, state) -> (state, delivered_now_words); use
    ``_core`` variants.  Returns (state, counts [n_ticks, M]).  Keeping the
    curve as per-tick count reductions (instead of a per-peer first_tick
    array) removes the dominant memory traffic from the hot loop.  The
    state carry is donated (see flood_run).
    """
    def body(s, _):
        s2, delivered = step_core(params, s)
        counts = count_bits_per_position(delivered, n_msgs)
        return s2, counts
    state, counts = jax.lax.scan(body, state, None, length=n_ticks)
    return state, counts


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(1,))
def flood_run_batch(params: FloodParams, state: FloodState, n_ticks: int,
                    step_fn=flood_step) -> FloodState:
    """flood_run over B replicas stacked on a leading axis
    (models/_batch.py stack_trees): one scan of the vmapped step, one
    donated resident carry."""
    vstep = jax.vmap(step_fn)

    def body(s, _):
        return vstep(params, s), None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


def make_circulant_step_core(offsets,
                             telemetry: "_telemetry.TelemetryConfig | None"
                             = None,
                             invariants:
                             "_invariants.InvariantConfig | None"
                             = None):
    """(params, state) -> (state, delivered_words) over a circulant
    graph.  Honors ``params.faults`` (models/faults.py): a down peer
    neither sends, receives, nor injects; a down link carries nothing
    that tick; partition windows cut cross-group edges.

    With ``telemetry`` (models/telemetry.py) the core returns
    ``(state, delivered_words, TelemetryFrame)`` carrying floodsub's
    applicable frame subset — payload copies sent, duplicates
    suppressed, estimated payload bytes, and the fault counters (the
    gossip/mesh/score fields stay zero).  The hop then runs as explicit
    per-edge rolls (instead of the fused propagation kernel) so per-edge
    copies are countable — the state trajectory stays bit-identical,
    and ``telemetry=None`` compiles the exact pre-telemetry core.
    The gather-based path threads telemetry too since round 10
    (make_gather_step_core).

    ``invariants`` (round 11): floodsub's delivery-group invariant
    subset folded into the armed state's carry — see
    make_gather_step_core."""
    offsets = tuple(int(o) for o in offsets)
    idx = {o: i for i, o in enumerate(offsets)}
    cinv = (tuple(idx[-o] for o in offsets)
            if all(-o in idx for o in offsets) else None)
    tel = telemetry
    ws = _telemetry.wire_sizes(tel) if tel is not None else None
    pc = jax.lax.population_count

    def telemetry_core(params: FloodParams, state: FloodState):
        fp = params.faults
        alive = aw = link = None
        src = state.have & params.fwd_words
        if fp is not None:
            alive = _faults.alive_mask(fp, state.tick)
            aw = _faults.alive_word(alive)
            link = _faults.link_ok_rows(fp, offsets, cinv, state.tick)
            src = src & aw[None, :]                        # sender up
        W = src.shape[0]
        sent_cnt = jnp.int32(0)
        recv_cnt = jnp.int32(0)
        w_rows = []
        for w in range(W):
            out = jnp.zeros_like(src[w])
            for c, off in enumerate(offsets):
                sent = (src[w] if link is None
                        else jnp.where(link[c], src[w], jnp.uint32(0)))
                rolled = jnp.roll(sent, off, axis=0)
                if aw is not None:
                    rolled = rolled & aw                   # receiver up
                out = out | rolled
                if tel.counters:
                    sent_cnt += pc(sent).sum(dtype=jnp.int32)
                    recv_cnt += pc(rolled).sum(dtype=jnp.int32)
            w_rows.append(out)
        heard = jnp.stack(w_rows, axis=0)
        new_state, delivered = _finish_step(params, state, heard,
                                            alive=alive)
        kw_f = {}
        if tel.counters:
            # accepted = what actually entered a peer's possession set;
            # the rest of the received copies were seen-cache (or
            # non-subscriber) drops
            accepted = (heard & ~state.have
                        & (params.fwd_words | params.deliver_words))
            kw_f.update(
                payload_sent=sent_cnt,
                dup_suppressed=recv_cnt - pc(accepted).sum(
                    dtype=jnp.int32))
            if tel.wire:
                kw_f["bytes_payload"] = (
                    sent_cnt.astype(jnp.float32)
                    * float(ws.payload_frame))
        if tel.latency_hist:
            kw_f["latency_hist"] = _telemetry.latency_histogram(
                delivered, params.publish_tick, state.tick,
                tel.latency_buckets)
        if tel.faults and fp is not None:
            kw_f["down_peers"] = (~alive).sum(dtype=jnp.int32)
            if link is not None:
                # UNITS: undirected mode halves the two views per
                # edge; directed mode counts DIRECTED edge-ticks (a
                # partition cut downs both directions and counts 2)
                kw_f["dropped_edge_ticks"] = (
                    (~link).sum(dtype=jnp.int32)
                    // (1 if fp.directed_drops else 2))
        return new_state, delivered, _telemetry.make_frame(**kw_f)

    def delayed_core(params: FloodParams, state: FloodState):
        # round-13 event-driven hop (models/delays.py): lag-l arrivals
        # replay the tick-(t-l) sends from the source-history ring,
        # keeping the edges whose sampled delay was exactly l+1.  The
        # send-time masks (alive/link) are recomputed statelessly at
        # the SEND tick; the receiver-alive mask applies at ARRIVAL.
        dlp = params.delays
        K = dlp.k_slots
        fp = params.faults
        tick = state.tick
        W, n = state.have.shape
        C = len(offsets)
        Z = jnp.uint32(0)
        alive = aw_now = None
        if fp is not None:
            alive = _faults.alive_mask(fp, tick)
            aw_now = _faults.alive_word(alive)
        count = tel is not None and tel.counters
        sent_cnt = recv_cnt = jnp.int32(0) if count else None
        w_rows = [jnp.zeros((n,), dtype=jnp.uint32) for _ in range(W)]
        link_now = src_now = None
        for lag in range(K):
            t_s = tick - lag
            src = (state.have if lag == 0
                   else jax.lax.dynamic_index_in_dim(
                       state.src_ring, jnp.mod(t_s, K), axis=0,
                       keepdims=False))
            src = src & params.fwd_words
            link_s = None
            if fp is not None:
                src = src & _faults.alive_word(
                    _faults.alive_mask(fp, t_s))[None, :]
                link_s = _faults.link_ok_rows(fp, offsets, cinv, t_s)
            if lag == 0:
                link_now, src_now = link_s, src
            dmask = _delays.arrive_now(dlp, (C, n), t_s, lag)
            for c, off in enumerate(offsets):
                m = (dmask[c] if link_s is None
                     else dmask[c] & link_s[c])
                for w in range(W):
                    sent = jnp.where(m, src[w], Z)
                    rolled = jnp.roll(sent, off, axis=0)
                    if aw_now is not None:
                        rolled = rolled & aw_now       # receiver up
                    w_rows[w] = w_rows[w] | rolled
                    if count:
                        recv_cnt = recv_cnt + pc(rolled).sum(
                            dtype=jnp.int32)
        if count:
            # payload copies SENT this tick (every delay class)
            for c in range(C):
                for w in range(W):
                    s0 = (src_now[w] if link_now is None
                          else jnp.where(link_now[c], src_now[w], Z))
                    sent_cnt = sent_cnt + pc(s0).sum(dtype=jnp.int32)
        heard = jnp.stack(w_rows, axis=0)
        ring = jax.lax.dynamic_update_slice_in_dim(
            state.src_ring, state.have[None], jnp.mod(tick, K),
            axis=0)
        new_state, delivered = _finish_step(params, state, heard,
                                            alive=alive,
                                            src_ring=ring)
        if tel is None:
            return new_state, delivered
        kw_f = {}
        if count:
            accepted = (heard & ~state.have
                        & (params.fwd_words | params.deliver_words))
            kw_f.update(
                payload_sent=sent_cnt,
                dup_suppressed=recv_cnt - pc(accepted).sum(
                    dtype=jnp.int32))
            if tel.wire:
                kw_f["bytes_payload"] = (
                    sent_cnt.astype(jnp.float32)
                    * float(ws.payload_frame))
        if tel.latency_hist:
            kw_f["latency_hist"] = _telemetry.latency_histogram(
                delivered, params.publish_tick, tick,
                tel.latency_buckets)
        if tel.faults and fp is not None:
            kw_f["down_peers"] = (~alive).sum(dtype=jnp.int32)
            if link_now is not None:
                kw_f["dropped_edge_ticks"] = (
                    (~link_now).sum(dtype=jnp.int32)
                    // (1 if fp.directed_drops else 2))
        return new_state, delivered, _telemetry.make_frame(**kw_f)

    def core(params: FloodParams, state: FloodState):
        if params.delays is not None:
            return delayed_core(params, state)
        if tel is not None:
            return telemetry_core(params, state)
        if params.faults is None:
            heard = propagate_circulant(state.have & params.fwd_words,
                                        offsets)
            return _finish_step(params, state, heard)
        fp = params.faults
        alive = _faults.alive_mask(fp, state.tick)
        aw = _faults.alive_word(alive)
        link = _faults.link_ok_rows(fp, offsets, cinv, state.tick)
        src = state.have & params.fwd_words & aw[None, :]  # sender up
        if link is None:
            # pure churn: every edge carries, so the hop IS the tuned
            # propagation kernel — only the endpoints are masked
            heard = propagate_circulant(src, offsets) & aw[None, :]
            return _finish_step(params, state, heard, alive=alive)
        w_rows = []
        for w in range(src.shape[0]):
            out = jnp.zeros_like(src[w])
            for c, off in enumerate(offsets):
                sent = jnp.where(link[c], src[w], jnp.uint32(0))
                out = out | jnp.roll(sent, off, axis=0)
            w_rows.append(out)
        heard = jnp.stack(w_rows, axis=0) & aw[None, :]    # receiver up
        return _finish_step(params, state, heard, alive=alive)

    if invariants is not None:
        return _invariants.wrap_step_delivery(core, invariants,
                                              "floodsub (circulant)")
    return core


def first_tick_matrix(state: FloodState, m: int) -> jnp.ndarray:
    """first_tick as [N, M] (strips word padding)."""
    return first_tick_to_matrix(state.first_tick, m)


def reach_counts(params: FloodParams, state: FloodState) -> jnp.ndarray:
    """Per-message delivered-peer counts: int32 [M]."""
    return reach_counts_from_first_tick(state.first_tick,
                                        params.publish_tick.shape[0])


def reach_by_hops(params: FloodParams, state: FloodState,
                  max_hops: int) -> jnp.ndarray:
    """[M, max_hops] cumulative deliveries by hop count — the
    reachability-vs-hops curve from BASELINE.md."""
    return reach_by_hops_from_first_tick(
        state.first_tick, params.publish_tick.shape[0], max_hops)
