"""Shared histogram-percentile helper (host side, stdlib only).

The single home of the bucket-percentile rank convention used by both
the device-histogram summaries (models/telemetry.py) and the tracestat
CLI gate (tools/tracestat.py): rank = min(k - 1, (k * p) // 100), the
same convention as percentiles over the expanded sorted sample, so a
unit-width-bucket histogram yields exactly the percentiles of the
underlying integer sample.  Kept jax- and numpy-free so tools can
import it without pulling the simulation stack.
"""

from __future__ import annotations


def hist_percentiles(hist, pcts=(50, 90, 99)) -> dict:
    """{"p50": ..., ..., "count": k} percentile BUCKET values from
    bucket counts (bucket value = index).  All-zero histograms report
    count 0 and percentiles None."""
    counts = [int(c) for c in hist]
    k = sum(counts)
    out = {"count": k}
    if k == 0:
        out.update({f"p{p}": None for p in pcts})
        return out
    cum = []
    run = 0
    for c in counts:
        run += c
        cum.append(run)
    for p in pcts:
        rank = min(k - 1, (k * p) // 100)
        out[f"p{p}"] = next(i for i, c in enumerate(cum)
                            if c >= rank + 1)
    return out
