"""Service observability plane (round 19).

Three pieces, one bundle:

* ``MetricsRegistry`` (metrics.py) — host counters / gauges /
  fixed-bucket histograms with atomic snapshot semantics, rendered as
  Prometheus text or JSON lines.
* ``SpanRecorder`` (spans.py) — per-request lifecycle spans with a
  propagated ``trace_id``, exported as Chrome trace-event JSON.
* ``ScrapeServer`` (scrape.py) — the loopback HTTP endpoint
  (``sweepd --metrics-port``).

``Observability`` bundles a registry + recorder so the serving stack
passes ONE handle around; it is cheap enough to be always-on (pure
host Python — device-side observability stays in models/telemetry.py,
whose counter frames round 19 makes delay-armed).
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .scrape import ScrapeServer
from .spans import SpanRecorder

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "Observability", "ScrapeServer", "SpanRecorder"]


class Observability:
    """One registry + one span recorder; ``scrape_server()`` wires
    them into an HTTP endpoint on demand."""

    def __init__(self, namespace: str = "pubsub",
                 span_capacity: int = 100_000):
        self.metrics = MetricsRegistry(namespace)
        self.spans = SpanRecorder(capacity=span_capacity)

    def scrape_server(self, *, host: str = "127.0.0.1",
                      port: int = 0) -> ScrapeServer:
        return ScrapeServer(self.metrics, self.spans, host=host,
                            port=port).start()
