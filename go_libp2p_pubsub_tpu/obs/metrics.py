"""The host-side metrics registry (round 19).

One process-local registry of named instruments — counters, gauges,
and fixed-bucket histograms — with ATOMIC snapshot semantics: every
mutation takes the registry's RLock, ``atomic()`` exposes the same
lock for multi-instrument updates, and ``snapshot()`` reads under it.
A scraper therefore never observes a half-applied update group: the
serving front end publishes its whole accounting vector (admitted /
served / errors / timeouts / transient / queued / parked) in one
``atomic()`` block, so the no-silent-drop identity holds on EVERY
scrape, including mid-flight ones during a concurrent burst.

Two render surfaces, one snapshot:

* ``render_prometheus()`` — the Prometheus text exposition format
  (``# HELP`` / ``# TYPE`` / samples, histograms as cumulative
  ``_bucket{le=...}`` + ``_sum`` + ``_count``).
* ``render_json_lines()`` — one JSON object per metric family, the
  line-protocol / artifact form (``{"cmd": "metrics"}`` and the
  bench's METRICS_r19.json scrape rows).

Instruments are host Python only — device counters stay in
models/telemetry.py frames; this registry is where those frames and
the serving counters become scrapeable.
"""

from __future__ import annotations

import json
import re
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _label_key(labels: dict) -> tuple:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"metrics: bad label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Instrument:
    """Base: a named family holding one value per label set."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str):
        self._reg = registry
        self.name = name
        self.help = help
        self._values: dict = {}

    def value(self, **labels):
        with self._reg._lock:
            return self._values.get(_label_key(labels), 0)

    def _samples(self):
        """Snapshot rows under the registry lock (caller holds it)."""
        return [{"labels": dict(key), "value": v}
                for key, v in sorted(self._values.items())]


class Counter(_Instrument):
    """Monotonic counter.  ``inc`` adds; ``set_total`` publishes an
    externally-maintained monotonic total (the mirroring form the
    serving front end uses so its whole accounting vector lands in one
    ``atomic()`` block)."""

    kind = "counter"

    def inc(self, v: float = 1, **labels) -> None:
        if v < 0:
            raise ValueError(
                f"metrics: counter {self.name} cannot decrease "
                f"(inc({v}))")
        key = _label_key(labels)
        with self._reg._lock:
            self._values[key] = self._values.get(key, 0) + v

    def set_total(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._reg._lock:
            if v < self._values.get(key, 0):
                raise ValueError(
                    f"metrics: counter {self.name} cannot decrease "
                    f"(set_total {v} < {self._values.get(key, 0)})")
            self._values[key] = v


class Gauge(_Instrument):
    """Point-in-time value (queue depth, resident buckets, ...)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._reg._lock:
            self._values[_label_key(labels)] = v

    def add(self, v: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._reg._lock:
            self._values[key] = self._values.get(key, 0) + v


class Histogram(_Instrument):
    """Fixed-bucket histogram: upper bounds set at registration (the
    in-scan telemetry convention — no dynamic rebucketing), per-label
    cumulative counts rendered Prometheus-style."""

    kind = "histogram"

    def __init__(self, registry, name, help, buckets):
        super().__init__(registry, name, help)
        ub = tuple(float(b) for b in buckets)
        if not ub or list(ub) != sorted(set(ub)):
            raise ValueError(
                f"metrics: histogram {name} buckets must be a "
                f"non-empty strictly-increasing sequence, got "
                f"{buckets!r}")
        self.buckets = ub

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._reg._lock:
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    row["counts"][i] += 1
                    break
            else:
                row["counts"][-1] += 1
            row["sum"] += float(v)
            row["count"] += 1

    def _samples(self):
        out = []
        for key, row in sorted(self._values.items()):
            out.append({"labels": dict(key),
                        "buckets": list(self.buckets),
                        "counts": list(row["counts"]),
                        "sum": row["sum"], "count": row["count"]})
        return out


class MetricsRegistry:
    """See the module docstring."""

    def __init__(self, namespace: str = "pubsub"):
        if namespace and not _NAME_RE.match(namespace):
            raise ValueError(
                f"metrics: bad namespace {namespace!r}")
        self.namespace = namespace
        self._lock = threading.RLock()
        self._metrics: dict[str, _Instrument] = {}

    def atomic(self):
        """The registry lock as a context manager: updates applied
        inside one ``with registry.atomic():`` block are visible to
        ``snapshot()`` all-or-nothing."""
        return self._lock

    # -- registration (idempotent by name; kind clashes are errors) ----

    def _register(self, cls, name, help, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"metrics: bad metric name {name!r}")
        with self._lock:
            got = self._metrics.get(name)
            if got is not None:
                if type(got) is not cls:
                    raise ValueError(
                        f"metrics: {name} already registered as "
                        f"{got.kind}, not {cls.kind}")
                return got
            inst = cls(self, name, help, **kw)
            self._metrics[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, buckets, help: str = ""
                  ) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    # -- snapshot + renders --------------------------------------------

    def snapshot(self) -> list[dict]:
        """Atomic point-in-time copy of every family: one dict per
        metric, ``{"name", "kind", "help", "samples": [...]}``."""
        with self._lock:
            return [{"name": self._full(m.name), "kind": m.kind,
                     "help": m.help, "samples": m._samples()}
                    for m in self._metrics.values()]

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def render_json_lines(self) -> str:
        return "".join(json.dumps(fam, sort_keys=True) + "\n"
                       for fam in self.snapshot())

    def render_prometheus(self) -> str:
        out = []
        for fam in self.snapshot():
            name = fam["name"]
            if fam["help"]:
                out.append(f"# HELP {name} {fam['help']}")
            out.append(f"# TYPE {name} {fam['kind']}")
            for s in fam["samples"]:
                if fam["kind"] == "histogram":
                    cum = 0
                    for ub, c in zip(s["buckets"] + ["+Inf"],
                                     s["counts"]):
                        cum += c
                        lb = dict(s["labels"], le=str(ub))
                        out.append(f"{name}_bucket{_lbl(lb)} {cum}")
                    out.append(
                        f"{name}_sum{_lbl(s['labels'])} {s['sum']}")
                    out.append(
                        f"{name}_count{_lbl(s['labels'])} "
                        f"{s['count']}")
                else:
                    out.append(f"{name}{_lbl(s['labels'])} "
                               f"{s['value']}")
        return "\n".join(out) + "\n"


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _lbl(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_esc(v)}"'
                    for k, v in sorted(labels.items()))
    return "{" + body + "}"
