"""The scrape endpoint (round 19): a daemon-thread HTTP server.

Serves the registry + span recorder over loopback HTTP so the
resident sweepd process is observable at runtime, not just post-hoc
in bench artifacts:

    GET /metrics        Prometheus text exposition
    GET /metrics.json   JSON lines, one metric family per line
    GET /trace.json     Chrome trace-event JSON (load in Perfetto)
    GET /healthz        204 liveness

``port=0`` binds an ephemeral port (``server.port`` is the bound
one).  stdlib only (http.server / ThreadingHTTPServer) — no new
dependencies; request logging is silenced (scrapes at 1/s would spam
the serving log).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["ScrapeServer"]


class ScrapeServer:
    def __init__(self, metrics, spans=None, *,
                 host: str = "127.0.0.1", port: int = 0):
        self.metrics = metrics
        self.spans = spans
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _send(self, code, body=b"", ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type",
                                 f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(200, outer.metrics.render_prometheus()
                               .encode())
                elif path == "/metrics.json":
                    self._send(200, outer.metrics.render_json_lines()
                               .encode(), "application/json")
                elif path == "/trace.json":
                    if outer.spans is None:
                        self._send(404, b"no span recorder attached\n")
                    else:
                        self._send(
                            200,
                            json.dumps(outer.spans.chrome_trace())
                            .encode(), "application/json")
                elif path == "/healthz":
                    self._send(204)
                else:
                    self._send(
                        404, b"paths: /metrics /metrics.json "
                             b"/trace.json /healthz\n")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "ScrapeServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval":
                                                      0.2},
            name="obs-scrape", daemon=True)
        self._thread.start()
        return self

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
