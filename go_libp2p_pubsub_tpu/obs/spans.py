"""Per-request spans with propagated trace ids (round 19).

``SpanRecorder`` gives every admitted request a ``trace_id`` that
rides its queue item and its result row, and records the request's
lifecycle as named phases::

    admit -> queue -> pad -> dispatch -> serve        (short path)
    admit -> queue -> dispatch -> serve | park        (long path)
    ... plus journal instants wherever the raw line is persisted

Durations are wall-clock (``time.perf_counter``); the export is the
Chrome trace-event JSON format (``{"traceEvents": [...]}``, complete
``"X"`` events in microseconds) so a single request is debuggable end
to end in ``chrome://tracing`` / Perfetto, and the per-bucket
device-dispatch wall timing is right there as the ``dispatch`` span's
``args.bucket``.

Thread-safe (one lock); bounded (``capacity`` events, oldest dropped
with a counted ``dropped_events`` so truncation is never silent).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from contextlib import contextmanager

__all__ = ["SpanRecorder"]

#: the request-lifecycle phases in order (the obsstat coverage check
#: asserts one ``admit`` per admitted request and a terminal event —
#: ``serve`` / ``park`` — for every trace that left the queue)
PHASES = ("admit", "queue", "pad", "dispatch", "serve", "park",
          "journal")

TERMINAL = ("serve", "park")


class SpanRecorder:
    def __init__(self, capacity: int = 100_000,
                 clock=time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self.capacity = capacity
        self._events: list[dict] = []
        self._open: dict = {}           # (trace_id, name) -> (t0, args)
        self._seq = itertools.count()
        self.dropped_events = 0
        self.phase_counts: dict[str, int] = {}
        self._traces: set = set()

    # -- trace ids -----------------------------------------------------

    def new_trace_id(self, hint=None) -> str:
        n = next(self._seq)
        tag = str(hint) if hint is not None else "req"
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in tag)[:48] or "req"
        return f"{safe}-{n:06d}"

    # -- recording -----------------------------------------------------

    def _push(self, ev: dict) -> None:
        self._traces.add(ev["args"]["trace_id"])
        name = ev["name"]
        self.phase_counts[name] = self.phase_counts.get(name, 0) + 1
        if len(self._events) >= self.capacity:
            self._events.pop(0)
            self.dropped_events += 1
        self._events.append(ev)

    def _event(self, trace_id, name, ph, ts, dur=None, **args):
        ev = {"name": name, "cat": "serving", "ph": ph,
              "ts": int(ts * 1e6), "pid": os.getpid(),
              "tid": zlib.crc32(str(trace_id).encode()) & 0x7FFFFFFF,
              "args": dict(args, trace_id=trace_id)}
        if dur is not None:
            ev["dur"] = max(int(dur * 1e6), 0)
        return ev

    def begin(self, trace_id, name, **args) -> None:
        with self._lock:
            self._open[(trace_id, name)] = (self._clock(), args)

    def end(self, trace_id, name, **more) -> float:
        """Close an open span; returns its duration in seconds.
        Ending a span that was never begun records a zero-length span
        (visible, not a crash — the recorder must never take the
        serving path down)."""
        now = self._clock()
        with self._lock:
            t0, args = self._open.pop((trace_id, name), (now, {}))
            self._push(self._event(trace_id, name, "X", t0,
                                   dur=now - t0, **dict(args, **more)))
            return now - t0

    @contextmanager
    def span(self, trace_id, name, **args):
        self.begin(trace_id, name, **args)
        try:
            yield
        finally:
            self.end(trace_id, name)

    def instant(self, trace_id, name, **args) -> None:
        with self._lock:
            ev = self._event(trace_id, name, "i", self._clock(),
                             **args)
            ev["s"] = "t"   # thread-scoped instant
            self._push(ev)

    # -- accounting / export -------------------------------------------

    def trace_count(self) -> int:
        with self._lock:
            return len(self._traces)

    def open_spans(self) -> int:
        with self._lock:
            return len(self._open)

    def summary(self) -> dict:
        """The artifact row obsstat checks: per-phase counts, distinct
        traces, terminal coverage, and the never-silent drop/open
        tallies."""
        with self._lock:
            phases = dict(self.phase_counts)
            return {
                "traces": len(self._traces),
                "events": len(self._events),
                "phases": phases,
                "terminal": sum(phases.get(p, 0) for p in TERMINAL),
                "open_spans": len(self._open),
                "dropped_events": self.dropped_events,
            }

    def chrome_trace(self) -> dict:
        with self._lock:
            return {"traceEvents": [dict(ev) for ev in self._events],
                    "displayTimeUnit": "ms",
                    "otherData": {"recorder": "go_libp2p_pubsub_tpu",
                                  "dropped_events":
                                      self.dropped_events}}

    def write_chrome_trace(self, path: str) -> None:
        from ..utils.artifacts import write_text_atomic
        write_text_atomic(path, json.dumps(self.chrome_trace()))
