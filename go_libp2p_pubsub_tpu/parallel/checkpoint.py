"""Preemption-tolerant execution (round 15): segmented scan runners
with checksummed on-disk snapshots and kill-safe resume.

The operational record behind this module is PERF_NOTES op-notes #1/#2:
TPU runs SIGTERM-killed mid-flight wedged the axon tunnel for 8.5+
hours, and a killed bench leaves truncated artifacts the ``*stat``
gates can only reject.  The fix is structural, not heuristic: the tick
horizon splits into S segments of one ``lax.scan`` each, and the FULL
carry — possession words, per-edge counters, mesh/backoff, scores, the
``[K, ...]`` delay lines, telemetry accumulators + histograms, the
invariant bitmask/first-violation tick, and the PRNG key phase (all of
it lives in the state pytree) — is snapshotted between segments.

Scan splitting is exact: ``run(s, a + b) == run(run(s, a), b)``
bit-for-bit, because the per-tick step is deterministic and every
tick-dependent quantity (PRNG lane hashing included) is keyed off
``state.tick``, which rides in the carry.  So a resumed run is
BIT-IDENTICAL to the uninterrupted one — the same fidelity bar the
attack suite's cold_restart and the invariant carry already hold the
sim to — on every execution path (XLA combined/split, pallas kernel,
flood circulant/gather, randomsub circulant/dense, sharded).

Snapshot format (one file per segment, ``<tag>-seg<NNNNNN>.ckpt``):

  line 1   JSON header: magic, version, config fingerprint (the
           gates_fingerprint machinery generalized — see
           ``config_fingerprint``), tick index, ticks_done, segment
           index, segment length, peer-axis layout (device count the
           state was placed on), payload byte length, payload CRC32.
  rest     npz payload of the packed leaves, keys = tree paths
           (utils/checkpoint.py's ``bits:dtype:key`` / ``raw::key``
           encoding for non-native dtypes), CRC-verified on read.

Writes are atomic (tmp + ``os.replace``), so a snapshot on disk is
never half-written; corrupted / truncated / fingerprint-mismatched
files are rejected BY NAME on read.  Snapshots hold host-side full
arrays, which is what makes D→D' re-placement free: save under a
4-device ``shard_sim`` placement, resume under 8 — the restore
``jax.device_put``s the host leaves into the new placement and the
carry-pinned sharded runners keep it there (tests/test_ckpt_runners.py
pins the digest across the move).

Kill-safety: ``install_kill_handlers`` converts SIGTERM/SIGINT into a
deferred stop flag; the segment loop finishes the in-flight segment,
flushes its snapshot, and raises ``CheckpointInterrupt`` — so a
``timeout -k`` grace sized to one segment never has to SIGKILL a
mid-operation TPU client (op-note #2's failure mode).  The runners
install the deferred handlers for the duration of the loop and restore
the previous handlers on exit; ``bench_suite`` installs them
process-wide.

The state carry is DONATED into each segment, like every runner in
models/ — callers that reuse the input state pass ``tree_copy``
(models/_batch.py).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import re
import signal
import threading
import zlib
from typing import ClassVar

import jax
import numpy as np

from functools import partial

from ..utils.checkpoint import _widen_exact

__all__ = [
    "MAGIC", "FORMAT_VERSION", "CheckpointConfig", "CheckpointInterrupt",
    "config_fingerprint", "snapshot_save", "snapshot_read",
    "latest_snapshot", "install_kill_handlers", "request_stop",
    "stop_requested", "clear_stop",
    "read_snapshot_chain",
    "journal_encode_line", "journal_decode_line", "read_journal",
    "ckpt_gossip_run", "ckpt_gossip_run_curve",
    "ckpt_gossip_run_fused",
    "ckpt_gossip_run_knob_batch", "ckpt_telemetry_run",
    "ckpt_flood_run", "ckpt_flood_run_curve",
    "ckpt_randomsub_run", "ckpt_randomsub_run_curve",
    "ckpt_sharded_gossip_run", "ckpt_sharded_gossip_run_fused",
    "ckpt_sharded_gossip_run_knob_batch",
    "segment_dispatch",
]

MAGIC = "tpu-pubsub-ckpt"
FORMAT_VERSION = 1

_SEG_RE = re.compile(r"-seg(\d{6})\.ckpt$")
_TAG_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


# --------------------------------------------------------------------------
# Config
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Host-side checkpoint spec for the segmented runners.

    directory: snapshot directory (created on first save).  A valid
        snapshot found here resumes the run from its tick.
    every: segment length in ticks; 0 = one segment spanning the whole
        horizon (checkpoint only at the end).  STATIC, not traced: the
        segment length is the scan length of each per-segment jit call,
        so each DISTINCT value is one compiled executable — equal
        segments share one, plus at most one remainder segment.  It
        never enters the computation as an operand (changing it must
        not change any tick's arithmetic — that is the bit-identity
        contract), which is the "traced-or-static" verdict the
        graftlint contract entry pins.
    keep: how many most-recent snapshots to retain (older segments are
        pruned after each save).
    fingerprint: config fingerprint stored in every header and required
        to match on resume — use ``config_fingerprint(cfg, score_cfg)``
        (the gates_fingerprint machinery generalized).  A mismatched
        snapshot is rejected by name, never silently re-run.
    tag: snapshot filename prefix, so one directory can hold snapshot
        chains for distinct runs.
    """

    directory: str
    every: int = 0
    keep: int = 2
    fingerprint: int = 0
    tag: str = "sim"
    async_write: bool = False
    full_every: int = 1

    # Machine-readable contract (tools/graftlint/contracts.py): every
    # field is host-side orchestration — "build-time", never traced.
    # ``every`` in particular is the segment-scheduling knob whose
    # static-only verdict the checker pins with a reject probe; the
    # fingerprint's resume-mismatch reject is probed by name against
    # snapshot_read.  ``async_write`` (round 16) overlaps segment k's
    # encode+CRC+write with segment k+1's compute behind the same
    # atomic tmp+fsync+os.replace contract (the device→host pull stays
    # synchronous — the donated carry is reused the moment the next
    # segment launches); ``full_every`` (round 16) writes a FULL
    # snapshot every Kth boundary and possession-churn deltas between
    # them — resume reconstructs the chain bit-identically, an
    # unusable chain (missing/corrupt base) is rejected by name.
    PATHS: ClassVar[tuple[str, ...]] = ("host",)
    CONTRACT: ClassVar[dict[str, object]] = {
        "directory": "build-time",
        "every": "build-time",
        "keep": "build-time",
        "fingerprint": "build-time",
        "tag": "build-time",
        "async_write": "build-time",
        "full_every": "build-time",
    }

    def __post_init__(self):
        if not self.directory:
            raise ValueError(
                "CheckpointConfig: directory must be a non-empty path "
                "(snapshots need somewhere to live)")
        if int(self.every) < 0:
            raise ValueError(
                f"CheckpointConfig: every={self.every} must be >= 0 "
                "(segment length in ticks; 0 = single segment)")
        if int(self.keep) < 1:
            raise ValueError(
                f"CheckpointConfig: keep={self.keep} must be >= 1 "
                "(resume needs at least the latest snapshot)")
        if not _TAG_RE.match(self.tag):
            raise ValueError(
                f"CheckpointConfig: tag={self.tag!r} must match "
                "[A-Za-z0-9_.-]+ (it is a filename prefix)")
        if not isinstance(self.async_write, bool):
            raise ValueError(
                f"CheckpointConfig: async_write={self.async_write!r} "
                "must be a bool (host-side writer mode, never traced)")
        if int(self.full_every) < 1:
            raise ValueError(
                f"CheckpointConfig: full_every={self.full_every} must "
                "be >= 1 (1 = every snapshot full; K > 1 = deltas "
                "between every Kth full)")


class CheckpointInterrupt(RuntimeError):
    """A SIGTERM/SIGINT arrived mid-run: the in-flight segment was
    finished and its snapshot flushed to ``path``.  Re-running the same
    call resumes from it; ``bench_suite`` catches this and exits 0."""

    def __init__(self, path: str, ticks_done: int, n_ticks: int):
        super().__init__(
            f"interrupted after {ticks_done}/{n_ticks} ticks; "
            f"snapshot flushed to {path}")
        self.path = path
        self.ticks_done = ticks_done
        self.n_ticks = n_ticks


# --------------------------------------------------------------------------
# Deferred signal handling
# --------------------------------------------------------------------------

_STOP = {"requested": False}


def request_stop(signum=None, frame=None) -> None:
    """Signal-handler body: defer the stop to the next segment
    boundary (never interrupts a device computation mid-flight)."""
    _STOP["requested"] = True


def stop_requested() -> bool:
    return _STOP["requested"]


def clear_stop() -> None:
    _STOP["requested"] = False


def install_kill_handlers():
    """Install the deferred SIGTERM/SIGINT handlers process-wide (main
    thread only — a no-op elsewhere, signal.signal would raise).
    Returns the list of (signum, previous_handler) pairs installed."""
    if threading.current_thread() is not threading.main_thread():
        return []
    prev = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        prev.append((sig, signal.signal(sig, request_stop)))
    return prev


def _restore_handlers(prev) -> None:
    for sig, handler in prev:
        signal.signal(sig, handler)


# --------------------------------------------------------------------------
# Journal lines (round 18)
# --------------------------------------------------------------------------

#: separator between a journal line's payload and its integrity suffix
#: (a tab never appears in the JSON-line protocols that use this)
_JOURNAL_SEP = "\t#crc32="


def journal_encode_line(raw: str) -> str:
    """One append-only journal line with the snapshot-header integrity
    treatment: the payload followed by its CRC32 suffix.  A line torn
    mid-write (the process died inside ``write``) fails the CRC and is
    detectable as exactly that — torn — instead of surfacing as a
    corrupt payload downstream (sweepd round 18: a torn tail line used
    to burn the scenario as a bad-JSON error row on replay)."""
    if "\n" in raw or "\r" in raw:
        raise ValueError("journal lines must be newline-free")
    return f"{raw}{_JOURNAL_SEP}{zlib.crc32(raw.encode()):08x}"


def journal_decode_line(line: str) -> str | None:
    """Recover the payload of one journal line, or ``None`` when the
    line is torn (CRC suffix mismatched or truncated mid-suffix).
    Lines written before the CRC suffix existed (no separator) are
    returned as-is — legacy journals replay unchanged."""
    payload, sep, crc_hex = line.rpartition(_JOURNAL_SEP)
    if not sep:
        return line  # pre-round-18 journal line: no integrity suffix
    try:
        want = int(crc_hex, 16)
    except ValueError:
        return None  # suffix itself torn mid-write
    if zlib.crc32(payload.encode()) != want:
        return None
    return payload


def read_journal(path: str) -> tuple[list[str], int]:
    """Read a CRC-suffixed journal: returns ``(payloads, n_torn)`` —
    every line whose integrity suffix verifies (or that predates the
    suffix), plus the count of torn lines dropped.  A missing journal
    is an empty one.

    Tail special case: a FINAL line with no separator at all (the
    writer died before reaching the suffix) decodes as a legacy line,
    but when any other line in the file carries the suffix the writer
    was demonstrably CRC-aware — so that tail is torn, not legacy.
    Only the tail gets this treatment: mid-file suffix-less lines can
    be a legacy journal continued by an upgraded writer."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except FileNotFoundError:
        return [], 0
    any_suffixed = any(_JOURNAL_SEP in ln for ln in lines)
    payloads, torn = [], 0
    for i, line in enumerate(lines):
        payload = journal_decode_line(line)
        if payload is None or (i == len(lines) - 1 and any_suffixed
                               and _JOURNAL_SEP not in line):
            torn += 1
        else:
            payloads.append(payload)
    return payloads, torn


# --------------------------------------------------------------------------
# Fingerprints
# --------------------------------------------------------------------------


def config_fingerprint(*objs) -> int:
    """Stable CRC32 fingerprint over config objects — the
    gates_fingerprint machinery (models/gossipsub.py) generalized to
    any mix of dataclasses, scalars, and tuples.  Scalar fields and
    (nested) tuples contribute their values; array-valued fields
    contribute only their type name (arrays belong in the payload, not
    the fingerprint).  ``config_fingerprint(cfg, score_cfg)`` is the
    recommended ``CheckpointConfig.fingerprint`` for gossip runs."""
    def desc(o):
        if o is None or isinstance(o, (bool, int, float, str)):
            return o
        if isinstance(o, tuple):
            return tuple(desc(x) for x in o)
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return (type(o).__name__,) + tuple(
                (f.name, desc(getattr(o, f.name)))
                for f in dataclasses.fields(o)
                if isinstance(getattr(o, f.name),
                              (bool, int, float, str, tuple,
                               type(None)))
                or dataclasses.is_dataclass(getattr(o, f.name)))
        return type(o).__name__
    return zlib.crc32(repr(tuple(desc(o) for o in objs)).encode())


# --------------------------------------------------------------------------
# Snapshot pack / unpack
# --------------------------------------------------------------------------


def _leaf_key(path) -> str:
    return "/".join(str(getattr(p, "name",
                                getattr(p, "key", getattr(p, "idx", p))))
                    for p in path)


def _leaf_dict(tree, prefix: str) -> dict[str, np.ndarray]:
    """Flatten a pytree to {``prefix/tree-path``: host array}.  A bare
    array flattens to the prefix alone."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for p, leaf in leaves:
        k = _leaf_key(p)
        out[prefix + "/" + k if k else prefix] = np.asarray(leaf)
    return out


def _encode_payload(by_key: dict[str, np.ndarray]) -> bytes:
    """{key: array} -> npz bytes, utils/checkpoint.py's encoding:
    non-native dtypes (bfloat16) stored as bit-views."""
    enc = {}
    for k, arr in by_key.items():
        if arr.dtype.kind not in "biufc?":
            enc["bits:" + arr.dtype.name + ":" + k] = arr.view(
                np.dtype(f"u{arr.dtype.itemsize}"))
        else:
            enc["raw::" + k] = arr
    buf = io.BytesIO()
    np.savez(buf, **enc)
    return buf.getvalue()


def _decode_payload(payload: bytes) -> dict[str, np.ndarray]:
    import ml_dtypes  # baked in with jax

    with np.load(io.BytesIO(payload)) as z:
        by_key = {}
        for full in z.files:
            tag, dtname, k = full.split(":", 2)
            arr = z[full]
            if tag == "bits":
                arr = arr.view(np.dtype(getattr(ml_dtypes, dtname)))
            by_key[k] = arr
    return by_key


def snapshot_save(path: str, header: dict,
                  by_key: dict[str, np.ndarray]) -> dict:
    """Write one snapshot file atomically: JSON header line (magic,
    version, payload length + CRC32 appended here) then the npz
    payload.  tmp + ``os.replace`` — a crash mid-write leaves the
    previous snapshot intact and at worst a ``.tmp`` orphan.  Returns
    the header as written (the delta chain links on its
    ``payload_crc32``)."""
    payload = _encode_payload(by_key)
    h = dict(header)
    h["magic"] = MAGIC
    h["version"] = FORMAT_VERSION
    h["payload_bytes"] = len(payload)
    h["payload_crc32"] = zlib.crc32(payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(json.dumps(h, sort_keys=True).encode() + b"\n")
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return h


def snapshot_read(path: str, expect_fingerprint: int | None = None
                  ) -> tuple[dict, dict[str, np.ndarray]]:
    """Read and verify one snapshot: returns (header, {key: array}).

    Every failure mode is rejected BY NAME: bad magic / unparseable
    header ("not a ... snapshot" / "corrupted"), short payload
    ("truncated"), CRC mismatch ("corrupted"), and — when
    ``expect_fingerprint`` is given — a config fingerprint mismatch
    ("fingerprint").  Never returns partially-verified state."""
    with open(path, "rb") as f:
        blob = f.read()
    nl = blob.find(b"\n")
    if nl < 0:
        raise ValueError(
            f"{path}: corrupted snapshot — no header line "
            "(not a checkpoint snapshot?)")
    try:
        header = json.loads(blob[:nl].decode("utf-8", errors="strict"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(
            f"{path}: corrupted snapshot — unparseable header "
            f"({e})") from e
    if not isinstance(header, dict) or header.get("magic") != MAGIC:
        raise ValueError(
            f"{path}: not a checkpoint snapshot (magic "
            f"{header.get('magic') if isinstance(header, dict) else None!r}"
            f" != {MAGIC!r})")
    if header.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"{path}: snapshot format version {header.get('version')!r} "
            f"is not the supported {FORMAT_VERSION}")
    payload = blob[nl + 1:]
    want_n = header.get("payload_bytes")
    if not isinstance(want_n, int) or len(payload) != want_n:
        raise ValueError(
            f"{path}: truncated snapshot — header promises {want_n} "
            f"payload bytes, file carries {len(payload)}")
    if zlib.crc32(payload) != header.get("payload_crc32"):
        raise ValueError(
            f"{path}: corrupted snapshot — payload CRC32 mismatch "
            "(bit flip or partial write)")
    if (expect_fingerprint is not None
            and int(header.get("fingerprint", -1))
            != int(expect_fingerprint)):
        raise ValueError(
            f"{path}: snapshot config fingerprint "
            f"{header.get('fingerprint')} != expected "
            f"{int(expect_fingerprint)} — this snapshot was taken "
            "under a different configuration; refusing to resume")
    try:
        by_key = _decode_payload(payload)
    except (ValueError, KeyError, OSError) as e:
        raise ValueError(
            f"{path}: corrupted snapshot — payload does not decode as "
            f"packed leaves ({e})") from e
    return header, by_key


def latest_snapshot(directory: str, tag: str):
    """(segment_index, path) of the highest-numbered ``tag``-prefixed
    snapshot in ``directory``, or None.  Validation happens at read."""
    if not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        if not name.startswith(tag + "-seg"):
            continue
        m = _SEG_RE.search(name)
        if m is None:
            continue
        idx = int(m.group(1))
        if best is None or idx > best[0]:
            best = (idx, os.path.join(directory, name))
    return best


# --------------------------------------------------------------------------
# Delta snapshots (round 16)
# --------------------------------------------------------------------------
#
# With ``CheckpointConfig.full_every = K > 1`` only every Kth boundary
# writes the full carry; the segments between encode AGAINST the
# previous snapshot, exploiting the sim's dominant churn pattern: the
# possession words are monotone (new bits only) and the mesh/backoff
# words move on heartbeat cadence, so most leaves change in a sparse
# fraction of their lanes per segment.  Per leaf the encoder stores
# (a) nothing when bit-identical to the base, (b) flat changed indices
# + values when under half the lanes moved, (c) the full leaf
# otherwise (or on any shape/dtype change — the concatenating aux
# arrays grow every segment).  The header links the chain
# (kind/base_segment/base_crc32/full_segment); reconstruction replays
# it from the full snapshot and verifies every link's CRC, so resume
# is bit-identical and a chain whose base is missing, corrupted, or
# CRC-divergent is rejected by the name "unusable delta chain".

_D_IDX = "~didx/"      # payload key prefix: flat changed indices
_D_VAL = "~dval/"      # payload key prefix: values at those indices


def _encode_delta(by_key: dict[str, np.ndarray],
                  base: dict[str, np.ndarray]
                  ) -> tuple[dict[str, np.ndarray], dict]:
    """Encode ``by_key`` against ``base``: (payload dict, header bits).
    Sparse entries ride as index/value pairs under the ``~didx/`` /
    ``~dval/`` key prefixes (the npz packer encodes their dtypes as
    usual); replaced and same keys are listed in the header."""
    payload: dict[str, np.ndarray] = {}
    same: list[str] = []
    replaced: list[str] = []
    sparse: list[str] = []
    for k, arr in by_key.items():
        b = base.get(k)
        if (b is None or b.shape != arr.shape
                or b.dtype != arr.dtype):
            replaced.append(k)
            payload[k] = arr
            continue
        av = arr.reshape(-1)
        bv = b.reshape(-1)
        # compare as raw bits so bf16/NaN payloads diff exactly
        au = av.view(np.dtype(f"u{arr.dtype.itemsize}")) \
            if arr.dtype.kind not in "biu?" else av
        bu = bv.view(np.dtype(f"u{arr.dtype.itemsize}")) \
            if arr.dtype.kind not in "biu?" else bv
        idx = np.flatnonzero(au != bu)
        if idx.size == 0:
            same.append(k)
        elif idx.size * 2 < av.size:
            sparse.append(k)
            payload[_D_IDX + k] = idx.astype(np.int64)
            payload[_D_VAL + k] = av[idx]
        else:
            replaced.append(k)
            payload[k] = arr
    removed = sorted(set(base) - set(by_key))
    bits = {"delta_same": sorted(same),
            "delta_sparse": sorted(sparse),
            "delta_replaced": sorted(replaced),
            "delta_removed": removed}
    return payload, bits


def _apply_delta(base: dict[str, np.ndarray], header: dict,
                 payload: dict[str, np.ndarray], path: str
                 ) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for k in header.get("delta_same", []):
        if k not in base:
            raise ValueError(
                f"{path}: unusable delta chain — delta keeps leaf "
                f"{k!r} the base snapshot does not carry")
        out[k] = base[k]
    for k in header.get("delta_replaced", []):
        out[k] = payload[k]
    for k in header.get("delta_sparse", []):
        if k not in base:
            raise ValueError(
                f"{path}: unusable delta chain — delta patches leaf "
                f"{k!r} the base snapshot does not carry")
        arr = base[k].copy().reshape(-1)
        idx = payload[_D_IDX + k]
        arr[idx] = payload[_D_VAL + k]
        out[k] = arr.reshape(base[k].shape)
    return out


def _chain_path(directory: str, tag: str, idx: int) -> str:
    return os.path.join(directory, f"{tag}-seg{idx:06d}.ckpt")


def read_snapshot_chain(directory: str, tag: str, idx: int,
                        expect_fingerprint: int | None = None
                        ) -> tuple[dict, dict[str, np.ndarray]]:
    """Read snapshot ``idx``, reconstructing through its delta chain
    when it is not a full snapshot.  Returns (header, by_key) exactly
    as ``snapshot_read`` does for a full one; every failure along the
    chain — a pruned/missing base, a corrupt link, a base whose CRC is
    not the one the delta was encoded against — raises by the name
    "unusable delta chain"."""
    path = _chain_path(directory, tag, idx)
    header, payload = snapshot_read(path, expect_fingerprint)
    if header.get("kind", "full") == "full":
        return header, payload
    full_idx = header.get("full_segment")
    if not isinstance(full_idx, int) or full_idx < 1 or full_idx > idx:
        raise ValueError(
            f"{path}: unusable delta chain — header names no valid "
            f"full_segment (got {full_idx!r})")
    chain = []     # [(path, header, payload)] from full to idx
    for j in range(full_idx, idx + 1):
        pj = _chain_path(directory, tag, j)
        try:
            hj, kj = snapshot_read(pj, expect_fingerprint)
        except FileNotFoundError as e:
            raise ValueError(
                f"{path}: unusable delta chain — link {pj} is missing "
                "(pruned with keep smaller than the chain, or deleted)"
            ) from e
        except ValueError as e:
            raise ValueError(
                f"{path}: unusable delta chain — link {pj} does not "
                f"read back ({e})") from e
        chain.append((pj, hj, kj))
    p0, h0, by_key = chain[0]
    if h0.get("kind", "full") != "full":
        raise ValueError(
            f"{path}: unusable delta chain — link {p0} should be the "
            "chain's full snapshot but is itself a delta")
    prev_crc = h0.get("payload_crc32")
    for pj, hj, kj in chain[1:]:
        if hj.get("kind", "full") != "delta":
            raise ValueError(
                f"{path}: unusable delta chain — link {pj} is not a "
                "delta (mixed chains: was the directory reused?)")
        if hj.get("base_crc32") != prev_crc:
            raise ValueError(
                f"{path}: unusable delta chain — link {pj} was "
                "encoded against a different base snapshot than the "
                "one on disk (CRC mismatch); refusing to resume")
        by_key = _apply_delta(by_key, hj, kj, pj)
        prev_crc = hj.get("payload_crc32")
    return chain[-1][1], by_key


# --------------------------------------------------------------------------
# Async double-buffered writer (round 16)
# --------------------------------------------------------------------------


class _AsyncWriter:
    """One in-flight snapshot write: ``submit`` joins the previous
    write (double-buffer depth 1 — segment k's encode+CRC+write
    overlaps segment k+1's device compute, never two writes), then
    launches the job on a daemon thread.  A failed write re-raises on
    the next submit or at ``drain`` — never silently dropped.  The
    device→host pull happens BEFORE submit (the caller passes host
    arrays): the donated carry is invalid the moment the next segment
    launches."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._err: BaseException | None = None

    def _run(self, job):
        try:
            job()
        except BaseException as e:       # surfaced on next submit/drain
            self._err = e

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, job) -> None:
        self._join()
        self._thread = threading.Thread(target=self._run, args=(job,),
                                        daemon=True)
        self._thread.start()

    def drain(self) -> None:
        """Block until the in-flight write (if any) has hit the disk;
        re-raise its failure.  The kill path calls this before raising
        CheckpointInterrupt, so the interrupt's snapshot is always
        durable by the time the exception escapes."""
        self._join()


def _restore_state(by_key: dict[str, np.ndarray], template,
                   shardings=None):
    """Rebuild the state pytree from packed ``state/...`` leaves using
    ``template``'s structure (the state from the same make_*_sim call).
    Shape mismatches, missing and extra leaves are named; dtypes must
    widen exactly (utils/checkpoint.py's rule).  With ``shardings``
    (a NamedSharding tree from shard_sim — possibly over a DIFFERENT
    device count than the save) the host leaves are placed directly
    into the new layout."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    out = []
    want_keys = set()
    for p, leaf in leaves:
        k = _leaf_key(p)
        k = "state/" + k if k else "state"
        want_keys.add(k)
        if k not in by_key:
            raise ValueError(f"snapshot missing state leaf {k!r} — "
                             "wrong sim configuration?")
        arr = by_key[k]
        want = np.asarray(leaf)
        if arr.shape != want.shape:
            raise ValueError(
                f"leaf {k!r}: snapshot {arr.dtype}{arr.shape} vs "
                f"template {want.dtype}{want.shape} — peer-axis "
                "layout or sim configuration mismatch")
        out.append(_widen_exact(arr, want.dtype, k, what="snapshot"))
    extra = sorted(k for k in by_key
                   if k.startswith("state/") and k not in want_keys)
    if extra:
        raise ValueError(
            f"snapshot has state leaves the template lacks: "
            f"{extra[:4]} — wrong sim configuration?")
    state = jax.tree_util.tree_unflatten(treedef, out)
    if shardings is not None:
        return jax.device_put(state, shardings)
    return jax.tree_util.tree_map(jax.numpy.asarray, state)


def _layout(state) -> dict:
    """Informational peer-axis layout for the header: how many devices
    the saved carry was placed on."""
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        device_set = getattr(sharding, "device_set", None)
        if device_set is not None:
            return {"devices": len(device_set)}
    return {"devices": 1}


# --------------------------------------------------------------------------
# The segment engine
# --------------------------------------------------------------------------


def _run_segmented(run_segment, state, n_ticks: int,
                   ckpt: CheckpointConfig, *, shardings=None,
                   has_aux: bool = False):
    """Drive ``run_segment(state, seg_len) -> (state, aux_piece|None)``
    over the horizon with snapshots between segments, resuming from the
    latest valid snapshot in ``ckpt.directory`` when one exists.

    aux pieces (per-tick scan outputs: curve counts, telemetry frames)
    are concatenated host-side along their leading tick axis and ride
    in the snapshot under ``aux/...`` keys, so a resumed curve/frames
    run returns the full-horizon arrays bit-identically."""
    if n_ticks < 0:
        raise ValueError(f"n_ticks={n_ticks} must be >= 0")
    every = int(ckpt.every) or max(int(n_ticks), 1)
    full_every = max(1, int(getattr(ckpt, "full_every", 1)))
    ticks_done = 0
    seg_idx = 0
    aux_acc: dict[str, np.ndarray] | None = None
    aux_treedef = None
    aux_keys: list[str] | None = None
    # delta-chain bookkeeping: the previous boundary's FULL host dict
    # (diff base) and its on-disk payload CRC (chain link)
    prev_by_key: dict[str, np.ndarray] | None = None
    last_crc: dict[str, object] = {"crc": None}

    found = latest_snapshot(ckpt.directory, ckpt.tag)
    if found is not None:
        seg_idx, path = found
        header, by_key = read_snapshot_chain(
            ckpt.directory, ckpt.tag, seg_idx,
            expect_fingerprint=ckpt.fingerprint)
        ticks_done = int(header["ticks_done"])
        prev_by_key = dict(by_key)
        last_crc["crc"] = header.get("payload_crc32")
        if ticks_done > n_ticks:
            raise ValueError(
                f"{path}: snapshot is {ticks_done} ticks in but the "
                f"requested horizon is only {n_ticks} — refusing to "
                "resume past the end (wrong directory or horizon?)")
        state = _restore_state(by_key, state, shardings)
        loaded_aux = {k: v for k, v in by_key.items()
                      if k.startswith("aux")}
        if loaded_aux:
            aux_acc = loaded_aux
        if has_aux and ticks_done == n_ticks and ticks_done > 0:
            raise ValueError(
                f"{path}: run already complete at {ticks_done} ticks — "
                "the per-tick outputs cannot be restructured without "
                "running a segment; point CheckpointConfig.directory "
                "somewhere fresh to rerun")

    prev_handlers = install_kill_handlers()
    writer = _AsyncWriter() if getattr(ckpt, "async_write", False) \
        else None
    try:
        while ticks_done < n_ticks:
            seg = min(every, n_ticks - ticks_done)
            state, piece = run_segment(state, seg)
            ticks_done += seg
            seg_idx += 1
            if piece is not None:
                pieces, aux_treedef = jax.tree_util.tree_flatten_with_path(
                    piece)
                pk = {}
                for p, leaf in pieces:
                    k = _leaf_key(p)
                    pk["aux/" + k if k else "aux"] = np.asarray(leaf)
                aux_keys = list(pk)
                if aux_acc is None:
                    aux_acc = pk
                elif set(aux_acc) != set(pk):
                    raise ValueError(
                        "resumed aux keys do not match this run's "
                        f"per-tick outputs: {sorted(aux_acc)[:3]} vs "
                        f"{sorted(pk)[:3]} — wrong snapshot chain?")
                else:
                    aux_acc = {k: np.concatenate([aux_acc[k], pk[k]],
                                                 axis=0) for k in pk}
            os.makedirs(ckpt.directory, exist_ok=True)
            path = os.path.join(ckpt.directory,
                                f"{ckpt.tag}-seg{seg_idx:06d}.ckpt")
            tick = jax.tree_util.tree_leaves(getattr(state, "tick",
                                                     ticks_done))
            is_full = (full_every == 1 or prev_by_key is None
                       or (seg_idx - 1) % full_every == 0)
            header = {
                "fingerprint": int(ckpt.fingerprint),
                "tick": int(np.asarray(tick[0]).reshape(-1)[0])
                        if tick else ticks_done,
                "ticks_done": ticks_done,
                "n_ticks": int(n_ticks),
                "segment": seg_idx,
                "every": int(ckpt.every),
                "layout": _layout(state),
                "tag": ckpt.tag,
                "kind": "full" if is_full else "delta",
                "full_every": full_every,
            }
            if not is_full:
                header["base_segment"] = seg_idx - 1
                header["full_segment"] = (
                    seg_idx - ((seg_idx - 1) % full_every))
            # the device→host pull is synchronous on purpose: the
            # donated carry is reused the moment the next segment
            # launches, so only encode+CRC+write may overlap compute
            by_key = _leaf_dict(state, "state")
            if aux_acc is not None:
                by_key.update(aux_acc)
            base = prev_by_key
            prev_by_key = by_key

            def job(path=path, header=header, by_key=by_key,
                    base=base, seg_idx=seg_idx):
                if header["kind"] == "delta":
                    payload, bits = _encode_delta(by_key, base)
                    header.update(bits)
                    # writes are serialized (depth-1 buffer), so the
                    # previous boundary's CRC is final by the time
                    # this job runs — async included
                    header["base_crc32"] = last_crc["crc"]
                    written = snapshot_save(path, header, payload)
                else:
                    written = snapshot_save(path, header, by_key)
                last_crc["crc"] = written["payload_crc32"]
                _prune(ckpt, seg_idx)

            if writer is None:
                job()
            else:
                writer.submit(job)
            if stop_requested() and ticks_done < n_ticks:
                if writer is not None:
                    writer.drain()
                raise CheckpointInterrupt(path, ticks_done, n_ticks)
        if writer is not None:
            writer.drain()
    finally:
        if writer is not None:
            try:
                writer.drain()
            except Exception:
                pass  # only reachable with a primary exception already
                      # unwinding — the normal path drained above
        _restore_handlers(prev_handlers)

    if not has_aux:
        return state, None
    if aux_treedef is None:
        # zero segments ran (n_ticks == 0, or everything was already
        # complete with no aux stored): nothing to restructure
        return state, None
    aux = jax.tree_util.tree_unflatten(
        aux_treedef, [aux_acc[k] for k in aux_keys])
    return state, aux


def _prune(ckpt: CheckpointConfig, newest: int) -> None:
    """Delete snapshots older than the ``keep`` window — EXCEPT the
    links the oldest kept snapshot's delta chain still needs: with
    ``full_every = K > 1`` the floor drops from the oldest kept index
    ``o`` to the full snapshot governing it, ``o - ((o-1) % K)``, so a
    kept delta can always be reconstructed."""
    if not os.path.isdir(ckpt.directory):
        return
    oldest = max(1, newest - int(ckpt.keep) + 1)
    full_every = max(1, int(getattr(ckpt, "full_every", 1)))
    floor = oldest - ((oldest - 1) % full_every)
    for name in os.listdir(ckpt.directory):
        if not name.startswith(ckpt.tag + "-seg"):
            continue
        m = _SEG_RE.search(name)
        if m is not None and int(m.group(1)) < floor:
            os.unlink(os.path.join(ckpt.directory, name))


# --------------------------------------------------------------------------
# Runners — segmented twins of the models/ and parallel/sharded.py ones
# --------------------------------------------------------------------------


# the reach helpers CANNOT donate their state operand: the knob-batch
# wrappers return that same final state to the caller next to the
# reach counts, so a donated (invalidated) buffer would poison the
# returned tree.  The O(N) carry lives exactly one extra call here —
# a [B, M] reduction, not a scan.
@jax.jit
def _batch_reach(params, state):  # graftlint: ignore[missing-donate]
    from ..models.gossipsub import reach_counts_from_have
    return jax.vmap(lambda p, s: reach_counts_from_have(p, s))(
        params, state)


@jax.jit
def _batch_reach_honest(params, state, honest):  # graftlint: ignore[missing-donate]
    from ..models.gossipsub import reach_counts_from_have
    return jax.vmap(reach_counts_from_have)(params, state, honest)


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def _sharded_batch_run(params, state, n_ticks: int, step, shardings):
    """sharded_gossip_run_knob_batch's scan WITHOUT the fused reach
    reduction — the segment body (reach runs once, at the end of the
    whole horizon, in the ckpt wrapper)."""
    vstep = jax.vmap(step)

    def body(s, _):
        s2 = vstep(params, s)[0]
        return jax.lax.with_sharding_constraint(s2, shardings), None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


def ckpt_gossip_run(params, state, n_ticks: int, step,
                    ckpt: CheckpointConfig):
    """gossip_run, segmented: identical final state (scan splitting is
    exact), snapshots between segments, resume from the latest one."""
    from ..models.gossipsub import gossip_run

    def seg(s, n):
        return gossip_run(params, s, n, step), None
    return _run_segmented(seg, state, n_ticks, ckpt)[0]


def ckpt_gossip_run_fused(params, state, n_ticks: int, window,
                          ckpt: CheckpointConfig):
    """gossip_run_fused, segmented: each segment is a scan of fused
    windows, so the segment boundary must land ON a window boundary —
    a ``CheckpointConfig.every`` that would split a fused window is
    refused by name (snapshots are taken between device dispatches;
    there is no mid-window carry to save).  Everything else is the
    ckpt_gossip_run contract: bit-identical resume, kill-safe."""
    from ..models.gossipsub import gossip_run_fused, _check_fused_horizon
    from ..models.plan import msg_ckpt_mid_window

    ticks_fused = int(getattr(window, "ticks_fused", 1))
    every = int(ckpt.every) or int(n_ticks)
    if every % ticks_fused != 0:
        # the refusal string is defined once, in the capability
        # planner (models/plan.py)
        raise ValueError(msg_ckpt_mid_window(int(ckpt.every),
                                             ticks_fused))
    _check_fused_horizon(n_ticks, ticks_fused)

    def seg(s, n):
        return gossip_run_fused(params, s, n, window), None
    return _run_segmented(seg, state, n_ticks, ckpt)[0]


def ckpt_gossip_run_curve(params, state, n_ticks: int, step,
                          ckpt: CheckpointConfig, n_msgs: int):
    """gossip_run_curve, segmented: per-segment count blocks are
    concatenated host-side (and carried through snapshots), so the
    returned [n_ticks, M] curve matches the single scan exactly."""
    from ..models.gossipsub import gossip_run_curve

    def seg(s, n):
        return gossip_run_curve(params, s, n, step, n_msgs)
    return _run_segmented(seg, state, n_ticks, ckpt, has_aux=True)


def ckpt_gossip_run_knob_batch(params, state, n_ticks: int, step,
                               ckpt: CheckpointConfig, honest=None):
    """gossip_run_knob_batch, segmented: the B stacked replicas advance
    via the batched scan, then the same per-replica reach reduction the
    single-shot runner fuses in runs once at the end — the reduction is
    a pure function of the final possession words, so (state, reach)
    match the unsegmented dispatch bit-for-bit."""
    from ..models.gossipsub import gossip_run_batch

    def seg(s, n):
        return gossip_run_batch(params, s, n, step), None
    state = _run_segmented(seg, state, n_ticks, ckpt)[0]
    if honest is None:
        reach = _batch_reach(params, state)
    else:
        reach = _batch_reach_honest(params, state, honest)
    return state, reach


def ckpt_telemetry_run(params, state, n_ticks: int, step,
                       ckpt: CheckpointConfig):
    """telemetry_run, segmented: frame leaves (per-tick accumulator
    readouts AND histogram planes) concatenate along the tick axis and
    ride in the snapshots, so the resumed full-horizon frames are
    bit-identical."""
    from ..models.telemetry import telemetry_run

    def seg(s, n):
        return telemetry_run(params, s, n, step)
    return _run_segmented(seg, state, n_ticks, ckpt, has_aux=True)


def ckpt_flood_run(params, state, n_ticks: int, step_fn,
                   ckpt: CheckpointConfig):
    from ..models.floodsub import flood_run

    def seg(s, n):
        return flood_run(params, s, n, step_fn), None
    return _run_segmented(seg, state, n_ticks, ckpt)[0]


def ckpt_flood_run_curve(params, state, n_ticks: int, step_core,
                         ckpt: CheckpointConfig, n_msgs: int):
    from ..models.floodsub import flood_run_curve

    def seg(s, n):
        return flood_run_curve(params, s, n, step_core, n_msgs)
    return _run_segmented(seg, state, n_ticks, ckpt, has_aux=True)


def ckpt_randomsub_run(params, state, n_ticks: int, step,
                       ckpt: CheckpointConfig):
    from ..models.randomsub import randomsub_run

    def seg(s, n):
        return randomsub_run(params, s, n, step), None
    return _run_segmented(seg, state, n_ticks, ckpt)[0]


def ckpt_randomsub_run_curve(params, state, n_ticks: int, step,
                             ckpt: CheckpointConfig, n_msgs: int):
    from ..models.randomsub import randomsub_run_curve

    def seg(s, n):
        return randomsub_run_curve(params, s, n, step, n_msgs)
    return _run_segmented(seg, state, n_ticks, ckpt, has_aux=True)


def ckpt_sharded_gossip_run(params, state, n_ticks: int, step,
                            shardings, ckpt: CheckpointConfig):
    """sharded_gossip_run, segmented.  Snapshots hold host-side FULL
    arrays (the save gathers), so resume re-places them under whatever
    ``shard_sim`` layout the caller built — including a different
    device count than the save (the D→D' restore contract)."""
    from .sharded import sharded_gossip_run

    def seg(s, n):
        return sharded_gossip_run(params, s, n, step, shardings), None
    return _run_segmented(seg, state, n_ticks, ckpt,
                          shardings=shardings)[0]


def ckpt_sharded_gossip_run_fused(params, state, n_ticks: int,
                                  window, shardings,
                                  ckpt: CheckpointConfig):
    """sharded_gossip_run_fused, segmented (round 17): segments scan
    RESIDENT windows on the mesh, so both composition contracts apply
    at once — the segment boundary must land ON a window boundary
    (the ckpt_gossip_run_fused mid-window refusal, by name: there is
    no mid-window carry to save while it sits in VMEM) and snapshots
    hold host-side FULL arrays so resume re-places under any device
    count (the D→D' restore contract)."""
    from ..models.gossipsub import _check_fused_horizon
    from .sharded import sharded_gossip_run_fused

    ticks_fused = int(getattr(window, "ticks_fused", 1))
    every = int(ckpt.every) or int(n_ticks)
    if every % ticks_fused != 0:
        raise ValueError(
            f"ckpt segment boundary mid-window: CheckpointConfig."
            f"every={int(ckpt.every)} is not a multiple of "
            f"ticks_fused={ticks_fused} — align the segment length to "
            "the fused window")
    _check_fused_horizon(n_ticks, ticks_fused)

    def seg(s, n):
        return sharded_gossip_run_fused(params, s, n, window,
                                        shardings), None
    return _run_segmented(seg, state, n_ticks, ckpt,
                          shardings=shardings)[0]


def ckpt_sharded_gossip_run_knob_batch(params, state, n_ticks: int,
                                       step, shardings,
                                       ckpt: CheckpointConfig,
                                       honest=None):
    """sharded_gossip_run_knob_batch, segmented (see
    ckpt_gossip_run_knob_batch for the end-of-run reach contract)."""
    def seg(s, n):
        return _sharded_batch_run(params, s, n, step, shardings), None
    state = _run_segmented(seg, state, n_ticks, ckpt,
                           shardings=shardings)[0]
    if honest is None:
        reach = _batch_reach(params, state)
    else:
        reach = _batch_reach_honest(params, state, honest)
    return state, reach


def segment_dispatch() -> dict:
    """The per-segment device dispatches by sim — what actually runs
    inside a segment (and what the graftlint jaxpr audit traces for
    the segmented variants: donation across segment boundaries, no
    64-bit avals, no host callbacks inside a segment)."""
    from ..models import floodsub as fl
    from ..models import gossipsub as gs
    from ..models import randomsub as rs
    return {
        "gossipsub": gs.gossip_run,
        "gossipsub-curve": gs.gossip_run_curve,
        "gossipsub-batch": gs.gossip_run_batch,
        "floodsub": fl.flood_run,
        "randomsub": rs.randomsub_run,
    }
