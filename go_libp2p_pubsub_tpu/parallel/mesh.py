"""Device mesh + sharding helpers.

The simulator's scaling axis is the number of simulated peers.  State is
peer-minor (the peer axis is the LAST axis of every hot array — [C, N]
masks/scores, [W, N] possession words; see models/_delivery.py), so
sharding is uniform: the axis whose extent equals n_peers splits over the
'peers' mesh axis, everything else replicates.  XLA inserts the
collectives (circulant rolls along the sharded peer axis become
collective-permutes of the shard-boundary slices — a few MB per step at
1M peers), which is the TPU-native replacement for the reference's
per-peer stream I/O (/root/reference/comm.go) — see SURVEY.md §5.8.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (PEER_AXIS,))


def peer_sharding(mesh: Mesh, ndim: int = 1, axis: int = 0) -> NamedSharding:
    spec = [None] * ndim
    spec[axis] = PEER_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_peer_tree(tree, mesh: Mesh, n_peers: int):
    """Place every array in the pytree: arrays with a peer-sized axis are
    sharded over that axis (the last such axis — peer-minor layout), the
    rest replicated."""
    repl = replicated(mesh)

    def place(x):
        # device_put handles host (numpy) data directly; going through
        # jnp.asarray first would commit it to the *default* backend,
        # which may not be the mesh's platform (e.g. a CPU dryrun mesh
        # on a TPU-default machine).
        arr = x if isinstance(x, jax.Array) else np.asarray(x)
        for axis in reversed(range(arr.ndim)):
            if arr.shape[axis] == n_peers:
                return jax.device_put(
                    arr, peer_sharding(mesh, arr.ndim, axis))
        return jax.device_put(arr, repl)

    return jax.tree_util.tree_map(place, tree)
