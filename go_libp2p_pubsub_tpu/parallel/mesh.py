"""Device mesh + sharding helpers.

The simulator's scaling axis is the number of simulated peers.  State is
peer-minor (the peer axis is the LAST axis of every hot array — [C, N]
masks/scores, [W, N] possession words; see models/_delivery.py), so
sharding is uniform: the axis whose extent equals n_peers splits over the
'peers' mesh axis, everything else replicates.  XLA inserts the
collectives (circulant rolls along the sharded peer axis become
collective-permutes of the shard-boundary slices — a few MB per step at
1M peers), which is the TPU-native replacement for the reference's
per-peer stream I/O (/root/reference/comm.go) — see SURVEY.md §5.8.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (PEER_AXIS,))


def peer_sharding(mesh: Mesh, ndim: int = 1, axis: int = 0) -> NamedSharding:
    spec = [None] * ndim
    spec[axis] = PEER_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def check_peer_divisible(n_peers: int, mesh: Mesh,
                         block: int | None = None) -> int:
    """Validate that ``n_peers`` splits evenly over the mesh's peer
    axis (and, when ``block`` is given, into whole kernel blocks per
    shard) — raising a NAMED error here instead of the shape blow-up
    GSPMD/shard_map would produce deep inside the scan.  Returns D."""
    D = int(mesh.shape[PEER_AXIS])
    if n_peers % D != 0:
        raise ValueError(
            f"shard_peer_tree: n_peers={n_peers} does not divide "
            f"evenly over the {D}-device '{PEER_AXIS}' mesh axis — "
            "pick n as a multiple of the device count (the peer axis "
            "splits into equal contiguous shards)")
    if block is not None and n_peers % (D * block) != 0:
        raise ValueError(
            f"shard_peer_tree: n_peers={n_peers} is not divisible by "
            f"D*block = {D}*{block} — the sharded kernel needs whole "
            f"receive blocks per shard; pick n as a multiple of "
            f"lcm(n_topics, {D * block})")
    return D


def check_fused_shardable(n_true: int, mesh: Mesh, offsets) -> int:
    """Round-17 twin of check_peer_divisible for the RESIDENT window:
    validate up front that the fused in-kernel-halo dispatch can place
    ``n_true`` peers over the mesh — even shards, whole lane tiles per
    shard, and a candidate reach the ring's halo exchange can cover —
    raising the same NAMED ``kernel_ticks_fused:`` errors the
    capability dispatch reports, instead of a shape blow-up inside
    shard_map.  Returns D."""
    from ..ops.pallas.receive import FUSED_SHARD_TILE, fused_halo_spec
    D = int(mesh.shape[PEER_AXIS])
    if n_true % D != 0:
        raise ValueError(
            f"kernel_ticks_fused: sharded windows need n_true "
            f"divisible by devices={D}; got {n_true}")
    S = n_true // D
    if S % FUSED_SHARD_TILE != 0:
        raise ValueError(
            f"kernel_ticks_fused: sharded windows need whole "
            f"{FUSED_SHARD_TILE}-lane tiles per shard "
            f"(S % {FUSED_SHARD_TILE} == 0); got S={S} at "
            f"n={n_true}, devices={D}")
    fused_halo_spec(offsets, S, D)   # raises by name on halo overreach
    return D


def shard_peer_tree(tree, mesh: Mesh, n_peers: int,
                    block: int | None = None):
    """Place every array in the pytree: arrays with a peer-sized axis are
    sharded over that axis (the LAST such axis — peer-minor layout, so a
    dense [N, N] array shards its trailing/receiver axis as documented),
    the rest replicated.  ``block`` additionally validates the sharded
    kernel's whole-blocks-per-shard divisibility up front."""
    check_peer_divisible(n_peers, mesh, block)
    repl = replicated(mesh)

    def place(x):
        # device_put handles host (numpy) data directly; going through
        # jnp.asarray first would commit it to the *default* backend,
        # which may not be the mesh's platform (e.g. a CPU dryrun mesh
        # on a TPU-default machine).
        arr = x if isinstance(x, jax.Array) else np.asarray(x)
        for axis in reversed(range(arr.ndim)):
            if arr.shape[axis] == n_peers:
                return jax.device_put(
                    arr, peer_sharding(mesh, arr.ndim, axis))
        return jax.device_put(arr, repl)

    return jax.tree_util.tree_map(place, tree)
