"""Device mesh + sharding helpers.

The simulator's scaling axis is the number of simulated peers; every state
array leads with the peer dimension, so sharding is uniform: peer-major
arrays split over the 'peers' mesh axis, everything else replicates.  XLA
inserts the collectives (the neighbor gather becomes an all-gather of the
bitpacked possession words — a few MB per step at 1M peers), which is the
TPU-native replacement for the reference's per-peer stream I/O
(/root/reference/comm.go) — see SURVEY.md §5.8.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (PEER_AXIS,))


def peer_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(PEER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_peer_tree(tree, mesh: Mesh, n_peers: int):
    """Place every array in the pytree: leading-dim==n_peers arrays are
    sharded over the peer axis, the rest replicated."""
    peer = peer_sharding(mesh)
    repl = replicated(mesh)

    def place(x):
        arr = jax.numpy.asarray(x)
        if arr.ndim >= 1 and arr.shape[0] == n_peers:
            return jax.device_put(arr, peer)
        return jax.device_put(arr, repl)

    return jax.tree_util.tree_map(place, tree)
