"""Whole-sim multi-chip sharding (round 14, ROADMAP direction 1).

``mesh.py`` places a (params, state) tree once; this module makes the
placement a CONTRACT for the whole run: PartitionSpec trees built by
the same last-peer-axis rule, and pinned runners whose scan carry is
re-constrained to the input sharding every tick — so the trajectory
stays sharded end to end with no per-tick resharding (GSPMD has no
freedom to move the carry; the circulant rolls lower to boundary
collective-permutes and the telemetry/invariant reductions to
all-reduces, which ``collective_stats`` counts out of the compiled
HLO).  Per shard the arithmetic is untouched — the sharded trajectory
is bit-identical to the single-device run (tests/test_sharded.py pins
D in {2, 4, 8} on the CPU mesh, both execution paths).

The runners mirror models/gossipsub.py's (donated carry, static step),
with one extra static leaf: the NamedSharding tree.  Knob-batched
states ([B, ..., N] leaves, replicated scalar knobs) shard under the
same rule — the peer axis is still the last peer-sized axis — which is
what lets sweepd serve scenario streams per-shard (``--devices``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import PEER_AXIS, check_peer_divisible, shard_peer_tree

__all__ = [
    "peer_spec", "peer_spec_tree", "named_sharding_tree", "shard_sim",
    "sharded_gossip_run", "sharded_gossip_run_curve",
    "sharded_gossip_run_fused", "sharded_gossip_run_curve_fused",
    "sharded_gossip_run_knob_batch", "collective_stats",
]


def peer_spec(shape, n_peers: int) -> P:
    """The placement rule as a PartitionSpec: the LAST axis whose
    extent equals ``n_peers`` splits over the peers mesh axis (a dense
    [N, N] array shards its trailing/receiver axis), everything else
    replicates."""
    spec = [None] * len(shape)
    for axis in reversed(range(len(shape))):
        if shape[axis] == n_peers:
            spec[axis] = PEER_AXIS
            return P(*spec)
    return P()


def peer_spec_tree(tree, n_peers: int):
    """PartitionSpec tree over a (params, state, ...) pytree — the
    spec-level twin of mesh.shard_peer_tree (same rule, no device
    placement)."""
    return jax.tree_util.tree_map(
        lambda x: peer_spec(jnp.shape(x), n_peers), tree)


def named_sharding_tree(tree, mesh: Mesh, n_peers: int):
    """NamedSharding tree for ``tree`` on ``mesh`` — hashable (static
    jit leaf) because every node is a frozen dataclass/tuple of
    NamedShardings."""
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, peer_spec(jnp.shape(x),
                                                n_peers)), tree)


def shard_sim(params, state, mesh: Mesh, n_peers: int,
              block: int | None = None):
    """Validate divisibility (named errors, mesh.check_peer_divisible)
    and place BOTH trees.  Returns (params, state, state_shardings);
    pass the shardings to the pinned runners below."""
    check_peer_divisible(n_peers, mesh, block)
    params = shard_peer_tree(params, mesh, n_peers)
    state = shard_peer_tree(state, mesh, n_peers)
    return params, state, named_sharding_tree(state, mesh, n_peers)


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def sharded_gossip_run(params, state, n_ticks: int, step, shardings):
    """gossip_run with the carry PINNED: every tick's new state is
    re-constrained to ``shardings`` (the input placement), so the whole
    scan runs sharded with no per-tick resharding.  Donated like every
    runner — the sharded buffers are reused in place."""
    def body(s, _):
        s2 = step(params, s)[0]
        return jax.lax.with_sharding_constraint(s2, shardings), None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4, 5), donate_argnums=(1,))
def sharded_gossip_run_curve(params, state, n_ticks: int, step,
                             shardings, n_msgs: int):
    """gossip_run_curve, carry-pinned: per-tick delivered counts come
    back replicated (the popcount reduction over the sharded peer axis
    lowers to an all-reduce)."""
    from ..models.gossipsub import count_bits_per_position

    def body(s, _):
        s2, delivered = step(params, s)
        s2 = jax.lax.with_sharding_constraint(s2, shardings)
        return s2, count_bits_per_position(delivered, n_msgs)
    state, counts = jax.lax.scan(body, state, None, length=n_ticks)
    return state, counts


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def sharded_gossip_run_fused(params, state, n_ticks: int, window,
                             shardings):
    """gossip_run_fused on the mesh (round 17): the horizon chunks
    into ``n_ticks / window.ticks_fused`` RESIDENT windows — one
    in-kernel-halo pallas dispatch per shard per window — with the
    carry re-constrained to the input placement between windows.
    Build ``window`` with ``shard_mesh=``; the final state is
    bit-identical to the single-device ``gossip_run_fused`` (and so
    to the per-tick runners).  A horizon the window does not divide
    raises by name; carry donated as in every runner."""
    from ..models.gossipsub import _check_fused_horizon
    n_win = _check_fused_horizon(n_ticks, window.ticks_fused)

    def body(s, _):
        s2 = window(params, s)[0]
        return jax.lax.with_sharding_constraint(s2, shardings), None
    state, _ = jax.lax.scan(body, state, None, length=n_win)
    return state


@partial(jax.jit, static_argnums=(2, 3, 4, 5), donate_argnums=(1,))
def sharded_gossip_run_curve_fused(params, state, n_ticks: int,
                                   window, shardings, n_msgs: int):
    """gossip_run_curve_fused, carry-pinned on the mesh: per-tick
    delivered counts [n_ticks, M] come back replicated (the popcount
    reduction over the sharded peer axis lowers to an all-reduce),
    rows bit-identical to the per-tick runners'."""
    from ..models.gossipsub import (_check_fused_horizon,
                                    count_bits_per_position)
    n_win = _check_fused_horizon(n_ticks, window.ticks_fused)

    def body(s, _):
        s2, delivered = window(params, s)[:2]
        s2 = jax.lax.with_sharding_constraint(s2, shardings)
        return s2, jnp.stack([
            count_bits_per_position(delivered[t], n_msgs)
            for t in range(window.ticks_fused)])
    state, counts = jax.lax.scan(body, state, None, length=n_win)
    return state, counts.reshape(n_ticks, n_msgs)


@partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(1,))
def sharded_gossip_run_knob_batch(params, state, n_ticks: int, step,
                                  shardings, honest=None):
    """The sweep engine's device side on the mesh: B stacked scenario
    replicas ([B, ..., N] leaves sharded on the trailing peer axis,
    knob scalars replicated) advanced in ONE carry-pinned scan of the
    vmapped step, then the per-replica reach reduction (all-reduce
    over the peer shards).  Per replica and per shard the trajectory
    is bit-identical to the single-device gossip_run_knob_batch."""
    from ..models.gossipsub import reach_counts_from_have
    vstep = jax.vmap(step)

    def body(s, _):
        s2 = vstep(params, s)[0]
        return jax.lax.with_sharding_constraint(s2, shardings), None
    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    if honest is None:
        reach = jax.vmap(
            lambda p, s: reach_counts_from_have(p, s))(params, state)
    else:
        reach = jax.vmap(reach_counts_from_have)(params, state,
                                                 honest)
    return state, reach


_DTYPE_BYTES = {
    "pred": 1, "u8": 1, "s8": 1, "u16": 2, "s16": 2, "bf16": 2,
    "f16": 2, "u32": 4, "s32": 4, "f32": 4, "u64": 8, "s64": 8,
    "f64": 8,
}


def collective_stats(hlo_text: str) -> dict:
    """Count the boundary collectives in compiled HLO text and total
    their operand bytes — the number behind the VMEM-residency /
    boundary-traffic claim (tools/profile_bytes.py --devices,
    tools/shardstat.py).  Returns
    ``{op: {"count": k, "bytes": b}, ...}`` for the collective ops
    present (collective-permute, all-reduce, all-gather,
    reduce-scatter, all-to-all) plus a ``"total_bytes"`` sum.

    Bytes are per-op OUTPUT shapes (each instance is one boundary
    transfer of that shape per shard), parsed from lines like
    ``x = u32[16,125] collective-permute(...)``.
    """
    import re

    ops = ("collective-permute", "all-reduce", "all-gather",
           "reduce-scatter", "all-to-all")
    pat = re.compile(
        r"=\s+(?:\(([^)]*)\)|(\w+)\[([\d,]*)\][^ ]*)\s+"
        r"(" + "|".join(re.escape(o) for o in ops) + r")(?:-start)?\(")

    def shape_bytes(dtype: str, dims: str) -> int:
        n = 1
        for d in filter(None, dims.split(",")):
            n *= int(d)
        return n * _DTYPE_BYTES.get(dtype, 4)

    out: dict = {}
    for m in pat.finditer(hlo_text):
        tup, dtype, dims, op = m.groups()
        if tup is not None:
            b = 0
            for em in re.finditer(r"(\w+)\[([\d,]*)\]", tup):
                b += shape_bytes(*em.groups())
        else:
            b = shape_bytes(dtype, dims)
        ent = out.setdefault(op, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += b
    out["total_bytes"] = sum(
        v["bytes"] for k, v in out.items() if k != "total_bytes")
    return out
