"""Multi-host / multi-slice execution (ICI + DCN).

The reference scales across machines with one libp2p connection per peer
pair (SURVEY.md §5.8); this framework scales by sharding the simulated
peer axis across every chip JAX can see — XLA emits the collectives.
Within a pod slice the shard-boundary exchanges of the circulant rolls
ride ICI; across slices they ride DCN.  Because the peer axis is a ring,
arranging shards slice-major means each slice exchanges only its two
boundary shards' halo over DCN per tick (a few MB at 1M peers) — the DCN
analog of the reference keeping most traffic inside one datacenter.

Usage on a multi-host deployment:

    from go_libp2p_pubsub_tpu.parallel import multihost
    multihost.init()                   # jax.distributed.initialize()
    mesh = multihost.make_global_mesh()
    params = shard_peer_tree(params, mesh, n_peers)
    state = shard_peer_tree(state, mesh, n_peers)
    # the same jitted step as single-host; XLA partitions it globally

Every process must build the same mesh and run the same program (SPMD);
`jax.distributed.initialize` picks up coordinator/process envs on TPU
pods automatically.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from .mesh import PEER_AXIS


def init(coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None) -> None:
    """Initialize multi-host JAX.  On TPU pods all arguments are
    auto-detected from the environment; pass them explicitly for manual
    (e.g. CPU/GPU) clusters.  No-op if already initialized."""
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError:
        pass  # already initialized


def make_global_mesh() -> Mesh:
    """One 'peers' axis over every device of every process, ordered so
    ring-neighboring shards are physically adjacent: within a slice the
    order follows the ICI interconnect (mesh_utils), and slices are laid
    end-to-end so only slice-boundary halos cross DCN."""
    n = len(jax.devices())
    try:
        devices = mesh_utils.create_device_mesh((n,))
    except (ValueError, NotImplementedError):
        # mesh_utils only knows real accelerator topologies: ValueError
        # when it cannot factor the device count onto one, and
        # NotImplementedError for platforms with no topology table
        # (CPU/GPU test rigs).  The 1-D peers ring needs no ICI
        # ordering in that case — enumeration order is fine.  An
        # AssertionError, by contrast, is a mesh_utils bug and must
        # surface, not silently degrade the device ordering (round 15:
        # narrowed from the old blanket tuple).
        devices = np.array(jax.devices())
    return Mesh(devices.reshape(-1), (PEER_AXIS,))


def process_local_peer_slice(n_peers: int, mesh: Mesh | None = None) -> slice:
    """The contiguous block of simulated peers whose shards live on this
    process (for host-side IO: loading publish tables, writing trace
    shards).  Assumes the uniform peer-axis sharding of shard_peer_tree.

    The peer axis shards **per device**, so the process slice is the
    union of this process's per-device shards — NOT n/process_count
    peers: e.g. 1008 peers on 2 processes x 8 devices places 63 peers
    per device, so process 0 owns [0, 504).  The peer count must divide
    by the device count: jax.device_put (shard_peer_tree) rejects uneven
    NamedShardings on this stack, so we surface the same contract here."""
    if mesh is not None:
        devices = list(mesh.devices.reshape(-1))
    else:
        if jax.process_count() > 1:
            # jax.devices() enumerates process-major, but
            # make_global_mesh may topology-order devices differently —
            # guessing here would silently misattribute peers
            raise ValueError(
                "multi-process runs must pass the actual mesh so the "
                "slice follows its device order")
        devices = jax.devices()
    if n_peers % len(devices) != 0:
        raise ValueError(
            f"n_peers={n_peers} must divide evenly over {len(devices)} "
            "devices (uneven peer-axis shardings are rejected by "
            "device_put; pad the peer count)")
    per = n_peers // len(devices)
    pid = jax.process_index()
    mine = [k for k, d in enumerate(devices) if d.process_index == pid]
    if not mine:
        return slice(0, 0)  # this process holds no shard of the mesh
    starts = [min(k * per, n_peers) for k in mine]
    stops = [min((k + 1) * per, n_peers) for k in mine]
    lo, hi = min(starts), max(stops)
    if hi - lo != sum(b - a for a, b in zip(starts, stops)):
        raise ValueError(
            "this process's devices are not contiguous along the mesh "
            "peer axis; pass the actual mesh and keep make_global_mesh's "
            "slice-major device order")
    return slice(lo, hi)
